"""Model zoo: torchvision topology parity via exact parameter counts + shapes."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models import get_model, list_models

# torchvision parameter counts @ 1000 classes (conv+bn affine+fc), the
# strongest cheap topology-parity oracle available without weights.
TORCHVISION_PARAM_COUNTS = {
    "ResNet18": 11_689_512,
    "ResNet34": 21_797_672,
    "ResNet50": 25_557_032,
    "ResNet101": 44_549_160,
    "ResNet152": 60_192_808,
}

# ViT family added beyond the reference; ViT-B16 matches torchvision
# vit_b_16 (86.6M @ 1000 classes).
VIT_NAMES = {"ViT-Ti16", "ViT-S16", "ViT-B16"}


def _count(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


@pytest.mark.parametrize("name", ["ResNet18", "ResNet50"])
def test_param_count_parity(name):
    model = get_model(name, num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)), train=False)
    )
    assert _count(variables["params"]) == TORCHVISION_PARAM_COUNTS[name]


def test_all_names_resolve():
    assert set(list_models()) == (
        set(TORCHVISION_PARAM_COUNTS) | VIT_NAMES | {"TransformerLM"}
    )
    for name in list_models():
        get_model(name, num_classes=10)
    get_model("resnet50", num_classes=10)  # case-insensitive
    with pytest.raises(KeyError):
        get_model("VGG16", num_classes=10)


def test_vit_b16_param_count_parity():
    model = get_model("ViT-B16", num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)), train=False)
    )
    # torchvision vit_b_16 @ 1000 classes
    assert _count(variables["params"]) == 86_567_656


def test_forward_shapes_and_stages():
    model = get_model("ResNet18", num_classes=7)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 7)
    assert out.dtype == jnp.float32

    # train mode returns mutated batch_stats
    out, updated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    assert out.shape == (2, 7)
    assert "batch_stats" in updated


def test_bf16_compute_fp32_params():
    model = get_model("ResNet18", num_classes=5, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3), jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32  # master weights stay fp32
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32  # logits promoted for the loss
