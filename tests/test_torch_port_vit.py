"""torchvision ViT weight-port parity: torch eval logits == Flax eval logits.

Extends the pretrained-ingestion surface to the ViT family.  torchvision
itself isn't installed, so the torch side is a line-faithful twin of
``torchvision.models.VisionTransformer`` — same module names
(``conv_proj``, ``class_token``, ``encoder.pos_embedding``,
``encoder.layers.encoder_layer_{i}`` with ``ln_1 / self_attention /
ln_2 / mlp.{0,3}``, ``encoder.ln``, ``heads.head``) and the same packed
``in_proj`` MHA layout, which is exactly the contract
``import_torch_vit_state_dict`` targets.  Logit agreement with random
weights pins the QKV head-permutation, pre-LN wiring, GELU MLP, class-token
readout, and every transpose.
"""
import numpy as np
import pytest
import torch
import torch.nn as tnn

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.models.torch_port import (
    import_torch_vit_state_dict,
)

DIM, HEADS, DEPTH, PATCH, IMG = 192, 3, 4, 16, 64


class TorchEncoderLayer(tnn.Module):
    """torchvision EncoderBlock: pre-LN MHA + pre-LN MLP, named like the
    torchvision state_dict (ln_1 / self_attention / ln_2 / mlp.{0,3})."""

    def __init__(self, dim, heads, mlp_ratio=4.0):
        super().__init__()
        self.ln_1 = tnn.LayerNorm(dim, eps=1e-6)
        self.self_attention = tnn.MultiheadAttention(
            dim, heads, batch_first=True
        )
        self.ln_2 = tnn.LayerNorm(dim, eps=1e-6)
        hidden = int(dim * mlp_ratio)
        self.mlp = tnn.Sequential(
            tnn.Linear(dim, hidden),
            tnn.GELU(),
            tnn.Dropout(0.0),
            tnn.Linear(hidden, dim),
            tnn.Dropout(0.0),
        )

    def forward(self, x):
        y = self.ln_1(x)
        a, _ = self.self_attention(y, y, y, need_weights=False)
        x = x + a
        return x + self.mlp(self.ln_2(x))


class TorchViT(tnn.Module):
    def __init__(self, num_classes, dim=DIM, heads=HEADS, depth=DEPTH,
                 patch=PATCH):
        super().__init__()
        self.conv_proj = tnn.Conv2d(3, dim, patch, patch)
        self.class_token = tnn.Parameter(torch.zeros(1, 1, dim))
        n_tokens = (IMG // patch) ** 2 + 1

        class Encoder(tnn.Module):
            def __init__(self):
                super().__init__()
                self.pos_embedding = tnn.Parameter(
                    torch.empty(1, n_tokens, dim).normal_(std=0.02)
                )
                self.layers = tnn.ModuleDict(
                    {
                        f"encoder_layer_{i}": TorchEncoderLayer(dim, heads)
                        for i in range(depth)
                    }
                )
                self.ln = tnn.LayerNorm(dim, eps=1e-6)

        self.encoder = Encoder()
        self.heads = tnn.ModuleDict({"head": tnn.Linear(dim, num_classes)})

    def forward(self, x):
        p = self.conv_proj(x)  # [B, D, H/ps, W/ps]
        b, d, gh, gw = p.shape
        tokens = p.reshape(b, d, gh * gw).permute(0, 2, 1)
        cls = self.class_token.expand(b, -1, -1)
        x = torch.cat([cls, tokens], dim=1) + self.encoder.pos_embedding
        for i in range(len(self.encoder.layers)):
            x = self.encoder.layers[f"encoder_layer_{i}"](x)
        x = self.encoder.ln(x)
        return self.heads["head"](x[:, 0])


def _randomized_twin(num_classes=10, seed=0):
    torch.manual_seed(seed)
    tm = TorchViT(num_classes)
    with torch.no_grad():
        tm.class_token.normal_(0, 0.02)
    return tm


def test_vit_eval_logits_match_torch():
    tm = _randomized_twin()
    tm.eval()
    from pytorch_distributed_training_tpu.models.vit import ViT

    model = ViT(num_classes=10, patch_size=PATCH, embed_dim=DIM,
                depth=DEPTH, num_heads=HEADS)
    rng = np.random.default_rng(2)
    img = rng.standard_normal((4, IMG, IMG, 3)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, IMG, IMG, 3)))
    params = import_torch_vit_state_dict(
        variables, tm.state_dict(), num_heads=HEADS
    )
    out = np.asarray(
        model.apply({"params": params}, jnp.asarray(img), train=False)
    )
    with torch.no_grad():
        ref = tm(torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_vit_port_strictness():
    tm = _randomized_twin()
    from pytorch_distributed_training_tpu.models.vit import ViT

    model = ViT(num_classes=10, patch_size=PATCH, embed_dim=DIM,
                depth=DEPTH, num_heads=HEADS)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, IMG, IMG, 3)))

    missing = dict(tm.state_dict())
    missing.pop("encoder.layers.encoder_layer_0.ln_1.weight")
    with pytest.raises(KeyError, match="missing"):
        import_torch_vit_state_dict(variables, missing, num_heads=HEADS)

    extra = dict(tm.state_dict())
    extra["stray.weight"] = torch.zeros(3)
    with pytest.raises(KeyError, match="not consumed"):
        import_torch_vit_state_dict(variables, extra, num_heads=HEADS)

    wrong = {
        k: (torch.zeros(7, 7) if k.endswith("in_proj_weight") else v)
        for k, v in tm.state_dict().items()
    }
    with pytest.raises((ValueError, IndexError)):
        import_torch_vit_state_dict(variables, wrong, num_heads=HEADS)


@pytest.mark.slow
def test_vit_pretrained_config(tmp_path):
    """model.pretrained covers the ViT family through the Runner: the
    config-initialized state reproduces the twin's eval logits."""
    from pytorch_distributed_training_tpu.engine import Runner

    torch.manual_seed(1)
    tm = TorchViT(4, dim=192, heads=3, depth=12, patch=16)  # ViT-Ti16 dims
    with torch.no_grad():
        tm.class_token.normal_(0, 0.02)
    tm.eval()
    ckpt = tmp_path / "vit_ti16.pt"
    torch.save(tm.state_dict(), ckpt)

    class _SetupOnly(Runner):
        def _train_loop(self, iter_generator, train_cfg):
            self.captured = self.state

    cfg = {
        "dataset": {
            "name": "synthetic", "root": str(tmp_path), "n_classes": 4,
            "image_size": IMG, "n_samples": 64,
        },
        "training": {
            "optimizer": {"name": "AdamW", "lr": 3.0e-4, "weight_decay": 0.1},
            "lr_schedule": {"name": "cosine", "total_iters": 100},
            "train_iters": 2,
            "print_interval": 1,
            "val_interval": 2,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": False,
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ViT-Ti16", "pretrained": str(ckpt)},
    }
    runner = _SetupOnly(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9927",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    rng = np.random.default_rng(5)
    img = rng.standard_normal((4, IMG, IMG, 3)).astype(np.float32)
    out = np.asarray(
        runner.model.apply(
            {"params": runner.captured.params}, jnp.asarray(img), train=False
        )
    )
    with torch.no_grad():
        ref = tm(torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
