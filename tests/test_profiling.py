"""Config-gated jax.profiler trace hooks (SURVEY.md §5.1 rebuild item) and
the round-6 step-time decomposition + remat/fusion recovery oracles."""
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.engine import TraceProfiler

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_from_config_absent_returns_none():
    assert TraceProfiler.from_config({"batch_size": 16}) is None
    assert TraceProfiler.from_config({"profile": None}) is None


def test_trace_window_produces_profile(tmp_path):
    prof_dir = str(tmp_path / "trace")
    prof = TraceProfiler.from_config(
        {"profile": {"dir": prof_dir, "start_iter": 2, "n_iters": 3}}
    )
    assert prof is not None and prof.start_iter == 2 and prof.n_iters == 3

    f = jax.jit(lambda x: jnp.sin(x) @ x)
    x = jnp.ones((64, 64))
    for it in range(8):
        jax.block_until_ready(f(x))
        prof.after_step(it)
    prof.stop()  # idempotent: window already closed at iter 4

    # jax.profiler writes plugins/profile/<timestamp>/*.xplane.pb under dir
    found = [
        os.path.join(dp, fn)
        for dp, _, fns in os.walk(prof_dir)
        for fn in fns
    ]
    assert found, f"no trace files written under {prof_dir}"


def test_from_config_bad_values(tmp_path):
    import pytest

    with pytest.raises(ValueError, match="must be a mapping"):
        TraceProfiler.from_config({"profile": True})
    with pytest.raises(ValueError, match="profile.dir is required"):
        TraceProfiler.from_config({"profile": {"start_iter": 3}})


def test_zero_capture_close_rearms(tmp_path):
    """A stop() that caught no iterations (e.g. validation fired the moment
    the window opened) discards the window and retries afterwards."""
    prof = TraceProfiler(str(tmp_path / "t3"), start_iter=2, n_iters=2)
    prof.after_step(2)          # opens
    prof.stop()                 # interruption before any traced iteration
    assert not prof._active and not prof._done  # re-armed
    prof.after_step(3)          # reopens
    assert prof._active
    prof.after_step(4)
    prof.after_step(5)          # 5 >= 3+2 -> closes, 2 iterations captured
    assert prof._done
    prof.finalize()             # idempotent


def test_window_opens_once(tmp_path):
    prof = TraceProfiler(str(tmp_path / "t2"), start_iter=0, n_iters=1)
    prof.after_step(0)  # opens: traces iteration 1
    assert prof._active and not prof._done
    prof.after_step(1)  # closes after the traced iteration completes
    assert prof._done and not prof._active
    prof.after_step(2)  # no reopen
    assert not prof._active


# --------------------------------------------------------------------- #
# Round 6: programmatic step-time decomposition
# --------------------------------------------------------------------- #

_VOCAB, _SEQ, _BATCH = 128, 32, 2


def _tiny_lm(**kw):
    from pytorch_distributed_training_tpu.models.transformer_lm import (
        TransformerLM,
    )

    return TransformerLM(
        vocab_size=_VOCAB, max_len=_SEQ, embed_dim=32, depth=2, num_heads=4,
        dtype=jnp.float32, **kw,
    )


def _tiny_batch(seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, _VOCAB, (_BATCH, _SEQ + 1)).astype(np.int32)
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


def _single_device_step(lm, opt):
    """A faithful single-device LM train step (no shard_map — runs on the
    vanilla-jax tier-1 path): fwd CE, grad, optimizer update."""
    from pytorch_distributed_training_tpu.ops import cross_entropy_loss

    def loss_fn(p, tok, lab):
        logits = lm.apply({"params": p}, tok)
        return cross_entropy_loss(
            logits.reshape(-1, lm.vocab_size), lab.reshape(-1)
        )

    @jax.jit
    def step(params, opt_state, tok, lab):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, lab)
        new_p, new_o = opt.update(grads, opt_state, params, 1e-3)
        return new_p, new_o, loss

    return step


@pytest.mark.slow
def test_decompose_buckets_partition_step_time():
    """Bucket contract: non-negative, fixed key set, and the published
    buckets sum to step_ms within 10% (by construction they partition it
    exactly; the assertion pins the contract against refactors)."""
    from pytorch_distributed_training_tpu.engine.profiling import (
        decompose_lm_step,
    )
    from pytorch_distributed_training_tpu.optimizers import AdamW

    lm = _tiny_lm()
    inp, lab = _tiny_batch()
    params = lm.init(jax.random.PRNGKey(0), inp)["params"]
    opt = AdamW(lr=1e-3, weight_decay=0.1)
    opt_state = opt.init(params)
    step = _single_device_step(lm, opt)

    p, o = params, opt_state
    p, o, loss = step(p, o, inp, lab)  # compile
    float(loss)
    t0 = time.perf_counter()
    for _ in range(3):
        p, o, loss = step(p, o, inp, lab)
    float(loss)
    step_ms = (time.perf_counter() - t0) / 3 * 1e3

    out = decompose_lm_step(
        lm, opt, params, opt_state, inp, lab, step_ms, iters=2, windows=1
    )
    want = {
        "attention", "mlp_matmul", "elementwise", "ce_softmax", "optimizer",
        "host_infeed",
    }
    assert set(out["buckets"]) == want
    assert set(out["raw_ms"]) == want - {"host_infeed"}
    for k, v in out["buckets"].items():
        assert v >= 0.0, f"bucket {k} negative: {v}"
    for k, v in out["raw_ms"].items():
        assert v >= 0.0, f"raw {k} negative: {v}"
    total = sum(out["buckets"].values())
    assert abs(total - out["step_ms"]) <= 0.1 * out["step_ms"] + 0.01
    assert out["overlap_factor"] > 0


def test_decompose_respects_ema_fold():
    """The optimizer bucket times the step's REAL update: with an EMA decay
    and a fused optimizer it must route through update_with_ema (a crash
    here would mean the probe and the step diverge)."""
    from pytorch_distributed_training_tpu.engine.profiling import (
        decompose_lm_step,
    )
    from pytorch_distributed_training_tpu.optimizers import AdamW

    lm = _tiny_lm()
    inp, lab = _tiny_batch()
    params = lm.init(jax.random.PRNGKey(0), inp)["params"]
    opt = AdamW(lr=1e-3, weight_decay=0.1, fused=True)
    out = decompose_lm_step(
        lm, opt, params, opt.init(params), inp, lab, 100.0,
        iters=1, windows=1, ema=params, ema_decay=0.99,
    )
    assert out["buckets"]["optimizer"] >= 0.0


@pytest.mark.slow
def test_bench_decompose_cli(tmp_path):
    """End-to-end ``bench.py decompose`` at a tiny config: one JSON line
    whose buckets partition step_ms, plus the BENCH_DECOMP_OUT file."""
    import json

    out_path = tmp_path / "decomp.json"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PDT_JAX_COMPAT="1",  # inert on grafted JAX; enables the seed
        # shard_map path on vanilla installs (single device = exact)
        PYTHONPATH=_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        BENCH_LM_VOCAB="256", BENCH_LM_SEQ="64", BENCH_LM_BATCH="2",
        BENCH_LM_EMBED="32", BENCH_LM_DEPTH="2", BENCH_LM_HEADS="4",
        BENCH_ITERS="2", BENCH_WINDOWS="1", BENCH_DECOMP_ITERS="2",
        BENCH_COMPILE_CACHE="0",
        BENCH_DECOMP_OUT=str(out_path),
    )
    env.pop("XLA_FLAGS", None)  # single-device: fastest + exact under compat
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "decompose"],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["unit"] == "ms/step"
    total = sum(out["buckets"].values())
    assert abs(total - out["step_ms"]) <= 0.1 * out["step_ms"] + 0.01
    assert all(v >= 0 for v in out["buckets"].values())
    assert json.loads(out_path.read_text())["buckets"] == out["buckets"]


# --------------------------------------------------------------------- #
# Round 6: remat policies + fused tails + fused optimizer parity oracles
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("policy", ["nothing", "dots", "dots_saveable"])
def test_remat_loss_parity(policy):
    """Remat changes WHERE activations come from (store vs recompute),
    never their values: >=10 training steps with remat on must track the
    remat-off trajectory to 1e-5."""
    from pytorch_distributed_training_tpu.ops import cross_entropy_loss

    inp, lab = _tiny_batch()

    def run(lm):
        params = lm.init(jax.random.PRNGKey(0), inp)["params"]

        def loss_fn(p):
            logits = lm.apply({"params": p}, inp)
            return cross_entropy_loss(
                logits.reshape(-1, lm.vocab_size), lab.reshape(-1)
            )

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(loss_fn)(p)
            return jax.tree_util.tree_map(lambda w, d: w - 0.1 * d, p, g), loss

        losses = []
        for _ in range(10):
            params, loss = step(params)
            losses.append(float(loss))
        return losses

    base = run(_tiny_lm(remat=False))
    remat = run(_tiny_lm(remat=True, remat_policy=policy))
    np.testing.assert_allclose(remat, base, rtol=0, atol=1e-5)


def test_fused_tails_parity():
    """model.fused_tails swaps elementwise tails into Pallas kernels with
    an IDENTICAL parameter tree: same init values, and logits + grads
    match the plain path on the same params."""
    from pytorch_distributed_training_tpu.ops import cross_entropy_loss

    inp, lab = _tiny_batch()
    plain = _tiny_lm(fused_tails=False)
    fused = _tiny_lm(fused_tails=True)
    p_plain = plain.init(jax.random.PRNGKey(0), inp)["params"]
    p_fused = fused.init(jax.random.PRNGKey(0), inp)["params"]
    assert jax.tree_util.tree_structure(p_plain) == jax.tree_util.tree_structure(
        p_fused
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p_plain), jax.tree_util.tree_leaves(p_fused)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def loss_fn(lm):
        def f(p):
            logits = lm.apply({"params": p}, inp)
            return cross_entropy_loss(
                logits.reshape(-1, lm.vocab_size), lab.reshape(-1)
            )

        return jax.jit(jax.value_and_grad(f))

    l0, g0 = loss_fn(plain)(p_plain)
    l1, g1 = loss_fn(fused)(p_plain)  # SAME params through the fused graph
    assert abs(float(l0) - float(l1)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-4
        )


def _mixed_tree(seed=0):
    rng = np.random.default_rng(seed)

    def arr(shape, dt):
        return jnp.asarray(rng.standard_normal(shape), dt)

    return {
        "dense": {"kernel": arr((8, 16), jnp.float32), "bias": arr((16,), jnp.float32)},
        "emb": arr((32, 8), jnp.float32),
        "half": arr((5, 5), jnp.bfloat16),
    }


@pytest.mark.parametrize("opt_name", ["SGD", "AdamW"])
def test_fused_optimizer_bitwise(opt_name):
    """training.optimizer.fused concatenates same-dtype leaves into one
    update — pointwise math commutes with concat, so the result must be
    BITWISE identical to the per-leaf path over multiple steps, including
    the folded-EMA variant vs a post-hoc tree-map."""
    import pytorch_distributed_training_tpu.optimizers as O

    kw = dict(lr=0.1, weight_decay=1e-2)
    if opt_name == "SGD":
        kw["momentum"] = 0.9
    make = getattr(O, opt_name)
    ref, fus = make(**kw), make(**kw, fused=True)
    params_r = params_f = _mixed_tree()
    ema_r = ema_f = _mixed_tree(1)
    state_r, state_f = ref.init(params_r), fus.init(params_f)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            np.random.default_rng(2).standard_normal(p.shape), p.dtype
        ),
        params_r,
    )
    d = 0.99
    for _ in range(3):
        params_r, state_r = ref.update(grads, state_r, params_r, 0.05)
        ema_r = jax.tree_util.tree_map(
            lambda e, p: d * e + (1.0 - d) * p, ema_r, params_r
        )
        params_f, state_f, ema_f = fus.update_with_ema(
            grads, state_f, params_f, 0.05, ema_f, d
        )
        for a, b in zip(
            jax.tree_util.tree_leaves((params_r, state_r, ema_r)),
            jax.tree_util.tree_leaves((params_f, state_f, ema_f)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_training_remat_config_key():
    """training.remat parses onto the model (none/block/dots/dots_saveable),
    rejects unknown values, non-LM configs, and conflicts with the
    model-section remat keys."""
    import types

    from pytorch_distributed_training_tpu.engine.topology import parse_topology

    class _DS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return np.zeros(_SEQ, np.int32), np.zeros(_SEQ, np.int32)

    def parse(remat=None, model_extra=None, model_name="TransformerLM"):
        model = {
            "name": model_name, "embed_dim": 32, "depth": 2, "num_heads": 4,
            "max_len": _SEQ,
        }
        if model_name != "TransformerLM":
            model = {"name": model_name}
        model.update(model_extra or {})
        cfg = {
            "dataset": {"name": "synthetic_text", "n_classes": _VOCAB,
                        "seq_len": _SEQ},
            "training": {"sync_bn": False, "batch_size": 8},
            "model": model,
        }
        if remat is not None:
            cfg["training"]["remat"] = remat
        r = types.SimpleNamespace(distributed=False, seq_len=_SEQ, world_size=1)
        parse_topology(r, cfg, cfg["training"], _DS())
        return r

    assert parse("none").model.remat is False
    assert parse("block").model.remat is True
    assert parse("block").model.remat_policy == "nothing"
    assert parse("dots").model.remat_policy == "dots"
    assert parse("dots_saveable").model.remat_policy == "dots_saveable"
    assert parse(None).model.remat is False  # absent key: default off
    with pytest.raises(ValueError, match="training.remat must be one of"):
        parse("typo")
    with pytest.raises(ValueError, match="not both"):
        parse("dots", model_extra={"remat": True})
    with pytest.raises(ValueError, match="only wired for the LM task"):
        parse("dots", model_name="ResNet18")
