"""Config-gated jax.profiler trace hooks (SURVEY.md §5.1 rebuild item)."""
import os

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.engine import TraceProfiler


def test_from_config_absent_returns_none():
    assert TraceProfiler.from_config({"batch_size": 16}) is None
    assert TraceProfiler.from_config({"profile": None}) is None


def test_trace_window_produces_profile(tmp_path):
    prof_dir = str(tmp_path / "trace")
    prof = TraceProfiler.from_config(
        {"profile": {"dir": prof_dir, "start_iter": 2, "n_iters": 3}}
    )
    assert prof is not None and prof.start_iter == 2 and prof.n_iters == 3

    f = jax.jit(lambda x: jnp.sin(x) @ x)
    x = jnp.ones((64, 64))
    for it in range(8):
        jax.block_until_ready(f(x))
        prof.after_step(it)
    prof.stop()  # idempotent: window already closed at iter 4

    # jax.profiler writes plugins/profile/<timestamp>/*.xplane.pb under dir
    found = [
        os.path.join(dp, fn)
        for dp, _, fns in os.walk(prof_dir)
        for fn in fns
    ]
    assert found, f"no trace files written under {prof_dir}"


def test_from_config_bad_values(tmp_path):
    import pytest

    with pytest.raises(ValueError, match="must be a mapping"):
        TraceProfiler.from_config({"profile": True})
    with pytest.raises(ValueError, match="profile.dir is required"):
        TraceProfiler.from_config({"profile": {"start_iter": 3}})


def test_zero_capture_close_rearms(tmp_path):
    """A stop() that caught no iterations (e.g. validation fired the moment
    the window opened) discards the window and retries afterwards."""
    prof = TraceProfiler(str(tmp_path / "t3"), start_iter=2, n_iters=2)
    prof.after_step(2)          # opens
    prof.stop()                 # interruption before any traced iteration
    assert not prof._active and not prof._done  # re-armed
    prof.after_step(3)          # reopens
    assert prof._active
    prof.after_step(4)
    prof.after_step(5)          # 5 >= 3+2 -> closes, 2 iterations captured
    assert prof._done
    prof.finalize()             # idempotent


def test_window_opens_once(tmp_path):
    prof = TraceProfiler(str(tmp_path / "t2"), start_iter=0, n_iters=1)
    prof.after_step(0)  # opens: traces iteration 1
    assert prof._active and not prof._done
    prof.after_step(1)  # closes after the traced iteration completes
    assert prof._done and not prof._active
    prof.after_step(2)  # no reopen
    assert not prof._active
