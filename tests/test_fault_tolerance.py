"""Fault-tolerance layer: every recovery path proven by deterministic injection.

Strategy (ISSUE: robustness tentpole): nothing here waits for production to
reproduce a failure — each path is driven by the engine/fault.py injection
registry (or a direct kill/stall) and the test asserts the RECOVERY, not
just the detection:

  - anomaly-step guard: a NaN batch leaves params bitwise unchanged; a
    grad-norm spike is gated by the trailing-median threshold; N
    consecutive anomalies roll the Runner back to the last checkpoint and
    the run still completes;
  - retrying checkpoint I/O: injected save failures are absorbed by the
    Retry policy and the final params bit-match an uninjected run;
  - worker respawn: a SIGKILLed pool worker is replaced and the epoch's
    batch sequence is bit-identical to an unkilled run;
  - serving degradation: submit-after-close fails fast, over-deadline
    requests resolve with TimeoutError while in-deadline ones complete,
    and the backlog bound sheds with OverloadedError;
  - watchdog: a stalled step fires exactly once, and never during warmup;
  - preemption: the latched signal set parses from YAML values, and the
    guard degrades to an inert flag off the main thread.
"""
import logging
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.engine import Runner, fault
from pytorch_distributed_training_tpu.engine.fault import (
    FaultInjectionError,
    FaultInjector,
)
from pytorch_distributed_training_tpu.utils.retry import Retry


@pytest.fixture(autouse=True)
def _fault_hygiene():
    """Process-global injector/counters must not leak between tests."""
    fault.install(None)
    fault.reset_counters()
    yield
    fault.install(None)
    fault.reset_counters()


@pytest.fixture
def one_device_mesh(monkeypatch):
    """A ONE-device mesh for the step/runner tests, with ``jax.shard_map``
    compat-grafted for this test only on pre-graft installs.

    The dev image's vanilla JAX lacks the toolchain's ``jax.shard_map``;
    the opt-in alias in utils/jax_compat.py has wrong pmean/psum autodiff
    on multi-device meshes but is EXACT when every collective spans a
    size-1 axis — and the guard/rollback/retry logic under test is
    device-count independent, so these tests pin it on one device rather
    than joining the known shard_map failure set (the graft is scoped via
    monkeypatch so the rest of the session keeps vanilla behavior)."""
    from pytorch_distributed_training_tpu.engine import paths
    from pytorch_distributed_training_tpu.parallel import make_mesh

    if not hasattr(jax, "shard_map"):
        from pytorch_distributed_training_tpu.utils import jax_compat

        monkeypatch.setenv("PDT_JAX_COMPAT", "1")
        jax_compat.install()
        wrapper = jax.shard_map
        del jax.shard_map
        monkeypatch.setattr(jax, "shard_map", wrapper, raising=False)
    mesh = make_mesh(jax.devices()[:1])
    monkeypatch.setattr(paths, "make_mesh", lambda *a, **kw: mesh)
    return mesh


# ======================================================================
# utils/retry.py
# ======================================================================
def test_retry_backoff_sequence():
    slept = []
    policy = Retry(
        attempts=4, backoff=0.1, max_backoff=0.3, jitter=0.0,
        sleep=slept.append,
    )
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("transient")
        return "ok"

    retries = []
    assert policy.call(flaky, on_retry=lambda a, e, d: retries.append(a)) == "ok"
    assert calls["n"] == 4
    # exponential 0.1, 0.2 then capped at max_backoff (jitter 0 -> exact)
    assert slept == pytest.approx([0.1, 0.2, 0.3])
    assert retries == [0, 1, 2]


def test_retry_allowlist_and_exhaustion():
    policy = Retry(attempts=3, backoff=0.0, jitter=0.0, sleep=lambda d: None)

    # non-allowlisted exception: no retry at all
    calls = {"n": 0}

    def bug():
        calls["n"] += 1
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        policy.call(bug)
    assert calls["n"] == 1

    # allowlisted but persistent: bounded attempts, original re-raised
    calls["n"] = 0

    def broken_disk():
        calls["n"] += 1
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        policy.call(broken_disk)
    assert calls["n"] == 3


def test_retry_non_retryable_classification():
    """Programming errors raise immediately even when the allowlist would
    catch them: ValueError/TypeError are deterministic — retrying burns
    the attempt budget and delays the traceback."""
    policy = Retry(
        attempts=3, backoff=0.0, jitter=0.0, sleep=lambda d: None,
        retry_on=(Exception,),  # broad allowlist that COVERS ValueError
    )
    calls = {"n": 0}

    def bad_argument():
        calls["n"] += 1
        raise ValueError("bad argument")

    with pytest.raises(ValueError, match="bad argument"):
        policy.call(bad_argument)
    assert calls["n"] == 1  # no retry: classified non-retryable

    calls["n"] = 0

    def wrong_type():
        calls["n"] += 1
        raise TypeError("wrong type")

    with pytest.raises(TypeError):
        policy.call(wrong_type)
    assert calls["n"] == 1

    # the denylist is a parameter: opting out restores plain allowlisting
    permissive = Retry(
        attempts=3, backoff=0.0, jitter=0.0, sleep=lambda d: None,
        retry_on=(ValueError,), non_retryable=(),
    )
    calls["n"] = 0
    with pytest.raises(ValueError):
        permissive.call(bad_argument)
    assert calls["n"] == 3  # retried to exhaustion


def test_retry_total_timeout_bounds_stacked_backoff():
    """total_timeout_s: stacked backoff must not outlive an external grace
    window (spot SIGTERM->SIGKILL gap, elastic emergency save).  A retry
    whose NEXT backoff sleep would cross the deadline re-raises the last
    failure immediately instead of sleeping past the budget — fake clock
    and sleep pin the arithmetic without wall time."""
    from pytorch_distributed_training_tpu.telemetry import (
        get_registry,
        reset_registry,
    )

    now = {"t": 0.0}
    slept = []

    def fake_sleep(d):
        slept.append(d)
        now["t"] += d

    reset_registry()
    policy = Retry(
        attempts=5, backoff=1.0, max_backoff=8.0, jitter=0.0,
        total_timeout_s=2.0, sleep=fake_sleep, clock=lambda: now["t"],
    )
    calls = {"n": 0}

    def broken_disk():
        calls["n"] += 1
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        policy.call(broken_disk)
    # attempt 0 fails -> backoff 1.0 fits (t=1.0); attempt 1 fails ->
    # backoff 2.0 would land at t=3.0 > 2.0 -> abandon, re-raise
    assert calls["n"] == 2
    assert slept == [1.0]
    reg = get_registry()
    assert reg.counter("retry_deadline_exceeded").value == 1
    assert reg.counter("retry_attempts").value == 1

    with pytest.raises(ValueError, match="total_timeout_s"):
        Retry(total_timeout_s=0.0)


# ======================================================================
# engine/fault.py — spec grammar and injector semantics
# ======================================================================
def test_fault_spec_parsing_and_one_shot():
    inj = FaultInjector(
        "nan_batch@2; kill_worker@4:1; stall_step@8:0.5; ckpt_fail@1:2"
    )
    assert inj.active
    assert inj.take("nan_batch", 1) is None
    assert inj.take("nan_batch", 2) == 1.0
    assert inj.take("nan_batch", 2) is None  # one-shot: consumed
    assert inj.take("kill_worker", 4) == 1.0
    assert inj.take("stall_step", 8) == 0.5
    # ckpt_fail@1:2 -> attempt ordinals 1 and 2 fail, 0 and 3 succeed
    inj.check_fail_point("ckpt_save")  # ordinal 0
    with pytest.raises(FaultInjectionError):
        inj.check_fail_point("ckpt_save")  # ordinal 1
    with pytest.raises(FaultInjectionError):
        inj.check_fail_point("ckpt_save")  # ordinal 2
    inj.check_fail_point("ckpt_save")  # ordinal 3
    # the restore point is independent of the save point
    inj.check_fail_point("ckpt_restore")
    assert not FaultInjector("").active
    # the async-write point (background writer thread) is its own ordinal
    # space too: ckpt_async_fail windows never consume ckpt_save attempts
    inj2 = FaultInjector("ckpt_async_fail@0:1")
    inj2.check_fail_point("ckpt_save")  # untouched by the async window
    with pytest.raises(FaultInjectionError):
        inj2.check_fail_point("ckpt_async_write")
    inj2.check_fail_point("ckpt_async_write")  # window exhausted


@pytest.mark.parametrize(
    "spec",
    [
        "nan_batch",  # missing @step
        "nan_batch@x",  # non-integer step
        "nan_batch@-1",  # negative step
        "nan_batch@3:1",  # nan_batch takes no arg
        "ckpt_fail@0:0",  # failure count must be >= 1
        "bogus@1",  # unknown kind
    ],
)
def test_fault_spec_errors(spec):
    with pytest.raises(ValueError):
        FaultInjector(spec)


def test_unknown_fault_kind_names_the_valid_kinds():
    """A typo'd kind must fail at SPEC-PARSE time with the full menu, not
    deep into the run when the fault would have fired."""
    with pytest.raises(ValueError) as ei:
        FaultInjector("kil_peer@3")
    msg = str(ei.value)
    for kind in ("nan_batch", "kill_worker", "stall_step", "kill_peer",
                 "sdc_flip", "ckpt_corrupt",
                 "serve_nan", "serve_raise", "serve_device_lost", "serve_hang",
                 "replica_down", "replica_hang",
                 "kv_transfer_stall", "kv_transfer_corrupt",
                 "prefill_replica_down",
                 "ckpt_fail", "restore_fail", "ckpt_async_fail"):
        assert kind in msg, f"{kind!r} missing from the error menu: {msg}"


def test_kill_peer_spec_parses_with_optional_rank():
    inj = FaultInjector("kill_peer@5")
    assert inj.take("kill_peer", 5) == -1.0  # default: any rank
    inj = FaultInjector("kill_peer@7:1")
    assert inj.take("kill_peer", 7) == 1.0
    assert inj.take("kill_peer", 7) is None  # one-shot


def test_fault_spec_comma_separator_and_duplicate_rejection():
    """The soak generator joins entries with ';' but hand-written specs
    (env vars, YAML) often use ',' — both parse, mixed freely.  The same
    kind@step twice is a spec bug (one-shot semantics make the second
    entry dead) and must fail at parse time."""
    inj = FaultInjector("nan_batch@2, kill_worker@4:1 ; stall_step@8:0.5")
    assert inj.take("nan_batch", 2) == 1.0
    assert inj.take("kill_worker", 4) == 1.0
    assert inj.take("stall_step", 8) == 0.5
    with pytest.raises(ValueError, match="duplicate"):
        FaultInjector("nan_batch@2;nan_batch@2")
    # same kind at DIFFERENT steps is the normal burst idiom
    assert FaultInjector("nan_batch@2;nan_batch@3").active


def test_injector_fired_and_pending_accounting():
    """fired()/pending() partition the spec exactly — the soak engine's
    accounting oracle (every armed fault fired, none left pending) reads
    these, so their balance is pinned here."""
    inj = FaultInjector("nan_batch@2;stall_step@5:0.1;ckpt_fail@0:2")
    assert inj.fired() == {}
    # fail-point entries account under their POINT name (ckpt_save), by
    # the attempt ordinals still ahead of the process
    assert inj.pending() == {
        "nan_batch": [2], "stall_step": [5], "ckpt_save": [0, 1],
    }
    inj.take("nan_batch", 2)
    with pytest.raises(FaultInjectionError):
        inj.check_fail_point("ckpt_save")  # ordinal 0
    assert inj.fired() == {"nan_batch": 1, "ckpt_save": 1}
    assert inj.pending() == {"stall_step": [5], "ckpt_save": [1]}
    with pytest.raises(FaultInjectionError):
        inj.check_fail_point("ckpt_save")  # ordinal 1
    inj.take("stall_step", 5)
    assert inj.pending() == {}
    assert inj.fired() == {"nan_batch": 1, "stall_step": 1, "ckpt_save": 2}
    # per-kind trigger counters mirror into the process registry
    c = fault.counters()
    assert c.get("fault_fired_nan_batch") == 1
    assert c.get("fault_fired_stall_step") == 1


def test_fault_spec_config_key_validated_at_parse_time():
    """A bad training.fault_tolerance.fault_spec fails when the CONFIG is
    parsed (topology.parse_fault_tolerance constructs an injector eagerly),
    not minutes later when the injector is first consulted."""
    import types

    from pytorch_distributed_training_tpu.engine.topology import (
        parse_fault_tolerance,
    )

    with pytest.raises(ValueError, match="unknown kind"):
        parse_fault_tolerance(
            types.SimpleNamespace(),
            {"fault_tolerance": {"fault_spec": "bogus@1"}},
        )
    r = types.SimpleNamespace()
    parse_fault_tolerance(
        r, {"fault_tolerance": {"fault_spec": "kill_peer@5; nan_batch@2"}}
    )
    assert r.fault_spec == "kill_peer@5; nan_batch@2"


# ======================================================================
# engine/steps.py — the anomaly guard inside the compiled step
# ======================================================================
def _tiny_guarded_step(anomaly_factor, mesh):
    from pytorch_distributed_training_tpu.engine import (
        build_train_step,
        init_train_state,
    )
    from pytorch_distributed_training_tpu.models.vit import ViT
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import (
        batch_sharding,
        replicated_sharding,
    )
    model = ViT(num_classes=8, patch_size=8, embed_dim=32, depth=1, num_heads=2)
    opt = SGD(lr=0.1, momentum=0.9)

    def fresh_state():
        state = init_train_state(
            model, opt, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
        )
        return jax.device_put(state, replicated_sharding(mesh))

    step = build_train_step(
        model, opt, lambda i: 0.1, mesh, sync_bn=False,
        anomaly_factor=anomaly_factor,
    )
    rng = np.random.default_rng(0)
    img = jax.device_put(
        rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
        batch_sharding(mesh, 4),
    )
    label = jax.device_put(
        rng.integers(0, 8, (16,)).astype(np.int32), batch_sharding(mesh, 1)
    )
    return fresh_state, step, img, label


@pytest.mark.slow
def test_nan_step_skipped_params_bitwise_unchanged(one_device_mesh):
    """anomaly_factor=0.0 arms the non-finite-only check: a NaN batch must
    leave params, momentum and the step counter BITWISE unchanged — nothing
    anomalous leaves the compiled step."""
    fresh_state, step, img, label = _tiny_guarded_step(0.0, one_device_mesh)
    state = fresh_state()
    before_params = jax.tree.map(np.asarray, state.params)
    before_mu = jax.tree.map(np.asarray, state.opt_state.momentum)

    nan_img = jnp.full(img.shape, jnp.nan, img.dtype)
    nan_img = jax.device_put(nan_img, img.sharding)
    state2, loss, gnorm, applied = step(state, nan_img, label, 0.0)
    assert float(applied) == 0.0
    assert not np.isfinite(float(loss))
    for a, b in zip(
        jax.tree.leaves(jax.tree.map(np.asarray, state2.params)),
        jax.tree.leaves(before_params),
    ):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(
        jax.tree.leaves(jax.tree.map(np.asarray, state2.opt_state.momentum)),
        jax.tree.leaves(before_mu),
    ):
        np.testing.assert_array_equal(a, b)
    assert int(state2.step) == 0  # the skipped update didn't count

    # the same compiled step APPLIES a clean batch (donated state: rebuild)
    state3, loss3, gnorm3, applied3 = step(fresh_state(), img, label, 0.0)
    assert float(applied3) == 1.0
    assert np.isfinite(float(loss3)) and np.isfinite(float(gnorm3))
    assert int(state3.step) == 1
    moved = jax.tree.leaves(jax.tree.map(np.asarray, state3.params))[0]
    assert not np.array_equal(moved, jax.tree.leaves(before_params)[0])


@pytest.mark.slow
def test_gnorm_spike_gated_by_trailing_reference(one_device_mesh):
    """grad_norm_factor > 0: the step is skipped iff the gradient norm
    exceeds factor x the host-fed reference; ref <= 0 means unarmed (the
    warmup steps before any history exists must always apply)."""
    fresh_state, step, img, label = _tiny_guarded_step(2.0, one_device_mesh)
    before = jax.tree.leaves(
        jax.tree.map(np.asarray, fresh_state().params)
    )[0]

    # unarmed reference: applied, and we learn the true gnorm
    _, _, gnorm, applied = step(fresh_state(), img, label, 0.0)
    g = float(gnorm)
    assert float(applied) == 1.0 and np.isfinite(g) and g > 0

    # reference far below the actual norm -> spike -> skipped, params frozen
    state2, _, _, applied2 = step(fresh_state(), img, label, g / 1000.0)
    assert float(applied2) == 0.0
    np.testing.assert_array_equal(
        jax.tree.leaves(jax.tree.map(np.asarray, state2.params))[0], before
    )

    # generous reference -> within threshold -> applied
    _, _, _, applied3 = step(fresh_state(), img, label, g * 1000.0)
    assert float(applied3) == 1.0


# ======================================================================
# Runner integration: injected faults end to end
# ======================================================================
def _ft_cfg(tmp_path, train_iters, fault_spec=None, ckpt=False, interval=2,
            anomaly=None, retry=None):
    cfg = {
        "dataset": {
            "name": "synthetic", "root": str(tmp_path), "n_classes": 4,
            "image_size": 16, "n_samples": 64,
        },
        "training": {
            "optimizer": {
                "name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4,
                "momentum": 0.9,
            },
            "lr_schedule": {
                "name": "multi_step", "milestones": [100], "gamma": 0.1,
            },
            "train_iters": train_iters,
            "print_interval": 10,
            "val_interval": 100,
            "batch_size": 16,
            "num_workers": 0,
            "sync_bn": False,
        },
        "validation": {"batch_size": 16, "num_workers": 0},
        "model": {"name": "ResNet18"},
    }
    ft = {}
    if anomaly is not None:
        ft["anomaly"] = anomaly
    if fault_spec is not None:
        ft["fault_spec"] = fault_spec
    if ft:
        cfg["training"]["fault_tolerance"] = ft
    if ckpt:
        cfg["training"]["checkpoint"] = {
            "dir": str(tmp_path / "ckpt"), "interval": interval,
            "resume": True,
        }
        if retry is not None:
            cfg["training"]["checkpoint"]["retry"] = retry
    return cfg


def _run(cfg):
    runner = Runner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9901",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    return runner


@pytest.mark.slow
def test_runner_nan_injection_skips_and_continues(tmp_path, one_device_mesh):
    """One injected NaN batch: the step is skipped (counted), training
    continues to completion, and the final params are finite."""
    cfg = _ft_cfg(
        tmp_path, train_iters=3, fault_spec="nan_batch@1",
        anomaly={"enabled": True},
    )
    runner = _run(cfg)
    assert runner.iter == 3
    c = fault.counters()
    assert c.get("injected_nan_batches") == 1
    assert c.get("skipped_steps") == 1
    assert "rollbacks" not in c
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, runner.state.params)):
        assert np.isfinite(leaf).all()
    # two applied steps: the skipped one did not advance the optimizer
    assert int(runner.state.step) == 2


@pytest.mark.slow
def test_runner_consecutive_anomalies_rollback_and_resume(tmp_path, one_device_mesh):
    """max_consecutive NaN steps trip the rollback: the Runner restores the
    last checkpoint, rebuilds the input stream, and completes the run."""
    cfg = _ft_cfg(
        tmp_path, train_iters=6, ckpt=True, interval=2,
        fault_spec="nan_batch@2;nan_batch@3;nan_batch@4",
        anomaly={"enabled": True, "max_consecutive": 3},
    )
    runner = _run(cfg)
    assert runner.iter == 6
    c = fault.counters()
    assert c.get("injected_nan_batches") == 3
    assert c.get("skipped_steps") == 3
    assert c.get("rollbacks") == 1
    # applied steps: 0,1 before the burst, then 4,5 after the rollback
    # replay (the one-shot faults are consumed, so the replay runs clean)
    assert int(runner.state.step) == 4
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, runner.state.params)):
        assert np.isfinite(leaf).all()


@pytest.mark.slow
def test_rollback_flushes_async_writer_before_restore(tmp_path, one_device_mesh,
                                                      monkeypatch):
    """Async checkpointing composes with the anomaly-guard rollback: the
    Runner must flush (drain, errors dropped) the background writer BEFORE
    restore_latest touches the checkpoint dir — two actors must never race
    on it, and a failed periodic save must not abort the recovery.  The
    rollback scenario itself must still complete end to end with async
    saves on."""
    from pytorch_distributed_training_tpu.engine.checkpoint import Checkpointer

    calls = []
    orig_drain = Checkpointer.drain
    orig_restore = Checkpointer.restore_latest

    def spy_drain(self, *a, **kw):
        calls.append(("drain", kw.get("raise_errors", a[0] if a else True)))
        return orig_drain(self, *a, **kw)

    def spy_restore(self, *a, **kw):
        calls.append(("restore", None))
        return orig_restore(self, *a, **kw)

    monkeypatch.setattr(Checkpointer, "drain", spy_drain)
    monkeypatch.setattr(Checkpointer, "restore_latest", spy_restore)

    cfg = _ft_cfg(
        tmp_path, train_iters=6, ckpt=True, interval=2,
        fault_spec="nan_batch@2;nan_batch@3;nan_batch@4",
        anomaly={"enabled": True, "max_consecutive": 3},
    )
    cfg["training"]["checkpoint"]["async"] = True
    runner = _run(cfg)
    assert runner.iter == 6
    assert fault.counters().get("rollbacks") == 1
    assert int(runner.state.step) == 4  # 0,1 + replayed 4,5 (burst skipped)

    # the rollback's restore (the startup resume also calls restore_latest,
    # on the then-empty dir) must be guarded IMMEDIATELY by the
    # error-dropping flush flavor
    assert any(
        calls[i] == ("drain", False) and calls[i + 1] == ("restore", None)
        for i in range(len(calls) - 1)
    ), f"no drain(raise_errors=False) directly before restore_latest: {calls}"


@pytest.mark.slow
def test_runner_rollback_without_checkpoint_is_loud(tmp_path, one_device_mesh):
    """Anomaly burst with no checkpoint configured: a descriptive error,
    not a silent loop."""
    cfg = _ft_cfg(
        tmp_path, train_iters=6, ckpt=False,
        fault_spec="nan_batch@1;nan_batch@2;nan_batch@3",
        anomaly={"enabled": True, "max_consecutive": 3},
    )
    with pytest.raises(RuntimeError, match="no training.checkpoint"):
        _run(cfg)


@pytest.mark.slow
def test_ckpt_save_failures_retried_final_state_matches(tmp_path, one_device_mesh):
    """Injected checkpoint-save failures are absorbed by the retry policy:
    training completes and the final params BIT-match an uninjected run
    (stronger than the 1e-6 loss bound the issue asks for)."""
    clean = _run(_ft_cfg(tmp_path / "a", train_iters=4, ckpt=True))
    want = jax.tree.map(np.asarray, clean.state.params)

    fault.reset_counters()
    cfg = _ft_cfg(
        tmp_path / "b", train_iters=4, ckpt=True,
        fault_spec="ckpt_fail@0:2",
        retry={"attempts": 3, "backoff": 0.0, "jitter": 0.0},
    )
    injected = _run(cfg)
    c = fault.counters()
    assert c.get("injected_ckpt_save_failures") == 2
    assert c.get("ckpt_retries") == 2
    assert injected.checkpointer.retries == 2
    got = jax.tree.map(np.asarray, injected.state.params)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_array_equal(a, b)
    # the retried save is real: a fresh run resumes from it
    fault.install(None)
    resumed = _run(_ft_cfg(tmp_path / "b", train_iters=4, ckpt=True))
    assert resumed.iter == 4


# ======================================================================
# data/worker_pool.py — dead-worker respawn
# ======================================================================
@pytest.mark.chaos
def test_worker_respawn_preserves_batch_sequence(tmp_path):
    """SIGKILL the (only) decode worker mid-epoch: the pool must respawn it
    with the same shard assignment and the epoch's batch stream must be
    bit-identical to an unkilled run — nothing dropped, nothing duplicated."""
    from pytorch_distributed_training_tpu.data import (
        DataLoader,
        RandomSampler,
        get_dataset,
    )

    ds = get_dataset(
        "synthetic", str(tmp_path), "train", n_classes=4, image_size=8,
        n_samples=64,
    )

    def make_dl():
        return DataLoader(
            ds, batch_size=4, sampler=RandomSampler(len(ds), seed=11),
            num_workers=1, drop_last=True, worker_mode="process",
        )

    ref_dl = make_dl()
    ref = list(ref_dl)
    ref_dl.close()
    assert len(ref) == 16

    dl = make_dl()
    try:
        it = iter(dl)
        got = [next(it), next(it)]
        pool = dl._pool
        pool._poll_seconds = 0.05  # fast dead-worker detection for the test
        os.kill(pool._procs[0].pid, signal.SIGKILL)
        got.extend(it)
        assert pool.respawns >= 1
        assert fault.counters().get("worker_respawns", 0) >= 1
        assert len(got) == len(ref)
        for (gi, gl), (ri, rl) in zip(got, ref):
            np.testing.assert_array_equal(gl, rl)
            np.testing.assert_array_equal(gi, ri)
    finally:
        dl.close()


@pytest.mark.chaos
def test_pool_respawn_budget_exhausted_is_loud(tmp_path):
    """A worker crash past max_respawns must raise, not respawn forever."""
    from pytorch_distributed_training_tpu.data import (
        DataLoader,
        RandomSampler,
        get_dataset,
    )

    ds = get_dataset(
        "synthetic", str(tmp_path), "train", n_classes=4, image_size=8,
        n_samples=32,
    )
    dl = DataLoader(
        ds, batch_size=4, sampler=RandomSampler(len(ds), seed=1),
        num_workers=1, drop_last=True, worker_mode="process",
    )
    try:
        it = iter(dl)
        next(it)
        pool = dl._pool
        pool._poll_seconds = 0.05
        pool.max_respawns = 0
        os.kill(pool._procs[0].pid, signal.SIGKILL)
        with pytest.raises(RuntimeError, match="respawn budget"):
            list(it)
    finally:
        dl.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_pool_close_escalates_wedged_worker(tmp_path):
    """close() must not hang on a wedged worker: a SIGSTOPped process never
    drains its sentinel, so the join times out and close escalates to
    terminate/kill (satellite: bounded close)."""
    from pytorch_distributed_training_tpu.data import (
        DataLoader,
        RandomSampler,
        get_dataset,
    )

    ds = get_dataset(
        "synthetic", str(tmp_path), "train", n_classes=4, image_size=8,
        n_samples=32,
    )
    dl = DataLoader(
        ds, batch_size=4, sampler=RandomSampler(len(ds), seed=1),
        num_workers=1, drop_last=True, worker_mode="process",
    )
    it = iter(dl)
    next(it)
    pool = dl._pool
    proc = pool._procs[0]
    os.kill(proc.pid, signal.SIGSTOP)  # wedged: alive but never progressing
    t0 = time.monotonic()
    dl.close()
    elapsed = time.monotonic() - t0
    assert not proc.is_alive()
    assert elapsed < 15.0  # bounded: join(2) + terminate/kill escalation


# ======================================================================
# serving/batcher.py — graceful degradation
# ======================================================================
def _echo_batcher(**kwargs):
    from pytorch_distributed_training_tpu.serving.batcher import DynamicBatcher

    return DynamicBatcher(
        run_batch=lambda reqs: [r.payload for r in reqs],
        max_batch_size=8, max_delay_ms=1.0, **kwargs,
    )


def test_batcher_submit_after_close_raises():
    b = _echo_batcher()
    assert b.submit("x").result(timeout=10) == "x"
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit("y")
    b.close()  # idempotent


@pytest.mark.chaos
def test_batcher_deadline_timeout_while_inflight_completes():
    """A request still queued past its deadline resolves with TimeoutError
    at collection time; requests inside their deadline complete normally."""
    from pytorch_distributed_training_tpu.serving.batcher import DynamicBatcher

    entered = threading.Event()
    release = threading.Event()

    def run_batch(reqs):
        entered.set()
        release.wait(timeout=30)
        return [r.payload for r in reqs]

    b = DynamicBatcher(run_batch=run_batch, max_batch_size=8, max_delay_ms=0.0)
    try:
        f1 = b.submit("first")
        assert entered.wait(timeout=10)  # flush thread is now blocked
        f2 = b.submit("doomed", deadline_ms=20.0)
        f3 = b.submit("patient")  # no deadline: waits forever
        time.sleep(0.08)  # let f2's deadline lapse while it sits queued
        release.set()
        assert f1.result(timeout=10) == "first"
        with pytest.raises(TimeoutError, match="deadline"):
            f2.result(timeout=10)
        assert f3.result(timeout=10) == "patient"
        assert b.timeouts == 1
    finally:
        release.set()
        b.close()


def test_batcher_load_shedding():
    """Beyond max_backlog, submit fails FAST with OverloadedError instead of
    growing an unbounded queue; queued requests still complete."""
    from pytorch_distributed_training_tpu.serving.batcher import (
        DynamicBatcher,
        OverloadedError,
    )

    entered = threading.Event()
    release = threading.Event()
    shed_events = []

    def run_batch(reqs):
        entered.set()
        release.wait(timeout=30)
        return [r.payload for r in reqs]

    b = DynamicBatcher(
        run_batch=run_batch, max_batch_size=8, max_delay_ms=0.0,
        max_backlog=1, on_shed=lambda: shed_events.append(1),
    )
    try:
        f1 = b.submit("a")
        assert entered.wait(timeout=10)  # "a" popped; the backlog is empty
        f2 = b.submit("b")  # fills the single backlog slot
        with pytest.raises(OverloadedError, match="backlog full"):
            b.submit("c")
        assert b.sheds == 1 and shed_events == [1]
        release.set()
        assert f1.result(timeout=10) == "a"
        assert f2.result(timeout=10) == "b"
    finally:
        release.set()
        b.close()


def test_serving_metrics_counters_in_snapshot():
    from pytorch_distributed_training_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.incr("timeouts")
    m.incr("timeouts")
    m.incr("sheds")
    snap = m.snapshot()
    assert snap["timeouts"] == 2
    assert snap["sheds"] == 1


# ======================================================================
# compound-failure hardening (chaos soak regressions — engine/chaos.py)
# ======================================================================
@pytest.mark.chaos
def test_emergency_save_bounded_when_async_write_wedged(tmp_path, monkeypatch):
    """Compound #1: peer loss with an async checkpoint write in flight.
    The emergency save's writer drain is bounded by
    ``emergency_drain_timeout_s`` — a write wedged in a dead filesystem op
    must not stall the peer-death escape hatch past the grace window.  The
    emergency dump still commits (its own subdir, rank-stamped meta) and
    the timeout is counted."""
    from pytorch_distributed_training_tpu.engine.checkpoint import Checkpointer

    ck = Checkpointer(
        str(tmp_path / "ckpt"), interval=1, async_save=True,
        emergency_drain_timeout_s=0.3,
    )
    monkeypatch.setattr(
        Checkpointer, "_write_async",
        lambda self, it, snapshot, extras: time.sleep(2.5),
    )
    state = {"params": np.arange(8, dtype=np.float32), "step": np.int64(4)}
    ck.save(0, state)  # enqueues the (wedged) background write
    t0 = time.monotonic()
    ck.save_emergency(4, state)
    wall = time.monotonic() - t0
    assert wall < 2.0, f"emergency save blocked {wall:.2f}s on the writer"
    assert fault.counters().get("emergency_drain_timeouts") == 1
    assert ck.latest_emergency() == 4
    emdir = tmp_path / "ckpt" / "emergency" / "4"
    assert any(p.name.startswith("meta_rank") for p in emdir.iterdir())
    ck.drain(raise_errors=False, timeout=5.0)  # let the wedge finish
    ck.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_sdc_during_rollback_replay_restores_post_rollback_timeline(
    tmp_path, one_device_mesh
):
    """Compound #2: an SDC flip lands DURING the anomaly-rollback replay.
    The integrity sentinel must recover to the POST-rollback timeline (the
    Runner rebases the retained snapshot after every rollback) — without
    the rebase, the restore would resurrect pre-rollback state and the
    final params/step would diverge from the flip-free run."""
    def cfg_for(sub, spec):
        cfg = _ft_cfg(
            tmp_path / sub, train_iters=6, ckpt=True, interval=2,
            fault_spec=spec,
            anomaly={"enabled": True, "max_consecutive": 3},
        )
        cfg["training"]["integrity"] = {
            "enabled": True, "check_interval": 6, "replicas": 3,
            "max_consecutive": 2,
        }
        return cfg

    burst = "nan_batch@2;nan_batch@3;nan_batch@4"
    clean = _run(cfg_for("clean", burst))
    want = jax.tree.map(np.asarray, clean.state.params)
    assert fault.counters().get("rollbacks") == 1

    fault.reset_counters()
    # the flip fires at iter 5 — inside the replay that follows the
    # rollback at iter 4 — and the step-5 integrity check catches it
    runner = _run(cfg_for("flip", burst + ";sdc_flip@5:0"))
    c = fault.counters()
    assert c.get("rollbacks") == 1
    assert c.get("injected_sdc_flips") == 1
    assert c.get("integrity_transient_flips") == 1, (
        "the sentinel never healed the replay-window flip"
    )
    assert int(runner.state.step) == int(clean.state.step)
    got = jax.tree.map(np.asarray, runner.state.params)
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
@pytest.mark.chaos
def test_watchdog_reenters_warmup_after_rollback(tmp_path, one_device_mesh):
    """Compound #4: the hung-step watchdog's trailing median survives a
    rollback ONLY by being discarded — post-restore replay steps run cold
    (recompiles) and judging them by the pre-fault median would turn the
    recovery into another false hang.  The Runner must reset() the
    watchdog on the rollback path; the reset re-enters warmup."""
    cfg = _ft_cfg(
        tmp_path, train_iters=6, ckpt=True, interval=2,
        fault_spec="nan_batch@2;nan_batch@3;nan_batch@4",
        anomaly={"enabled": True, "max_consecutive": 3},
    )
    cfg["training"]["fault_tolerance"]["watchdog"] = {
        "enabled": True, "factor": 4.0, "min_seconds": 0.5,
        "warmup": 3, "poll_seconds": 0.05,
    }
    runner = _run(cfg)
    assert fault.counters().get("rollbacks") == 1
    wd = runner._watchdog
    assert wd is not None
    assert wd.resets >= 1, "rollback did not reset the watchdog"
    assert wd.fires == 0, "replay was misjudged as a hang"


@pytest.mark.chaos
def test_watchdog_reset_reenters_warmup_semantics():
    """StepWatchdog.reset() drops the trailing window and the fired latch:
    the very next steps are warmup samples, unjudged however slow."""
    from pytorch_distributed_training_tpu.engine.watchdog import StepWatchdog

    fired = []
    with StepWatchdog(
        factor=2.0, min_seconds=0.05, window=8, warmup=2, poll_seconds=0.02,
        on_hang=lambda *a: fired.append(a),
    ) as wd:
        for i in range(2):
            wd.step_started(i)
            time.sleep(0.01)
            wd.step_finished()
        assert wd.trailing_median() is not None  # armed
        wd.reset()
        assert wd.resets == 1
        assert wd.trailing_median() is None  # history gone -> warmup
        wd.step_started(2)  # slow post-reset step: must NOT fire
        time.sleep(0.3)
        wd.step_finished()
        assert wd.fires == 0 and not fired


# ======================================================================
# engine/watchdog.py
# ======================================================================
@pytest.mark.chaos
def test_watchdog_fires_once_on_stalled_step():
    from pytorch_distributed_training_tpu.engine.watchdog import StepWatchdog

    fired = []
    with StepWatchdog(
        factor=2.0, min_seconds=0.15, window=8, warmup=2, poll_seconds=0.02,
        on_hang=lambda step, elapsed, limit: fired.append((step, elapsed, limit)),
    ) as wd:
        for i in range(2):  # warmup: two fast completed steps
            wd.step_started(i)
            time.sleep(0.01)
            wd.step_finished()
        assert wd.trailing_median() is not None
        wd.step_started(2)
        time.sleep(0.4)  # past max(min_seconds, factor * median)
        wd.step_finished()
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
    assert wd.fires == 1  # once per step index, not once per poll
    step, elapsed, limit = fired[0]
    assert step == 2
    assert elapsed > limit >= 0.15


@pytest.mark.chaos
def test_watchdog_unarmed_during_warmup():
    """The first compile takes minutes of legitimate wall time: before
    ``warmup`` completed samples exist the watchdog must never fire."""
    from pytorch_distributed_training_tpu.engine.watchdog import StepWatchdog

    fired = []
    with StepWatchdog(
        factor=2.0, min_seconds=0.05, window=8, warmup=3, poll_seconds=0.02,
        on_hang=lambda *a: fired.append(a),
    ) as wd:
        wd.step_started(0)  # no completed samples yet
        time.sleep(0.3)
        wd.step_finished()
        assert wd.fires == 0 and not fired


# ======================================================================
# engine/preemption.py — configurable signal set + degradation path
# ======================================================================
def test_parse_signals_accepts_names_numbers_and_lists():
    from pytorch_distributed_training_tpu.engine.preemption import PreemptionGuard

    parse = PreemptionGuard.parse_signals
    assert parse("SIGTERM") == (signal.SIGTERM,)
    assert parse("term") == (signal.SIGTERM,)  # SIG prefix + case optional
    assert parse(("SIGTERM",)) == (signal.SIGTERM,)
    assert parse(["SIGUSR1", "sigusr2"]) == (signal.SIGUSR1, signal.SIGUSR2)
    assert parse(int(signal.SIGTERM)) == (signal.SIGTERM,)
    with pytest.raises(ValueError, match="unknown signal name"):
        parse("SIGBOGUS")
    with pytest.raises(ValueError, match="invalid signal number"):
        parse(10_000)
    with pytest.raises(ValueError, match="at least one"):
        parse([])


def test_preemption_guard_inert_off_main_thread():
    """Signal handlers are installable only from the main thread: entered
    anywhere else the guard must degrade to an inert, still-settable flag
    (documented in engine/preemption.py) — not crash the run."""
    from pytorch_distributed_training_tpu.engine.preemption import PreemptionGuard

    before = signal.getsignal(signal.SIGTERM)
    result = {}

    def run():
        guard = PreemptionGuard(logger=logging.getLogger("test"))
        with guard as g:
            result["installed"] = g._installed
            result["triggered_initial"] = g.triggered
            g.triggered = True  # the watchdog's checkpoint_and_exit path
            result["settable"] = g.triggered

    t = threading.Thread(target=run)
    t.start()
    t.join(timeout=10)
    assert result == {
        "installed": False, "triggered_initial": False, "settable": True,
    }
    assert signal.getsignal(signal.SIGTERM) is before  # untouched


@pytest.mark.slow
def test_runner_parses_preemption_signals_from_yaml(tmp_path, one_device_mesh):
    """training.checkpoint.preemption_signals reaches the installed guard."""
    cfg = _ft_cfg(tmp_path, train_iters=2, ckpt=True)
    cfg["training"]["checkpoint"]["preemption_signals"] = ["SIGTERM", "USR1"]
    runner = _run(cfg)
    assert runner._preempt is not None
    assert runner._preempt.signals == (signal.SIGTERM, signal.SIGUSR1)
