"""Fleet router + replica failover oracles (serving/router.py, fleet.py).

The load-bearing oracle mirrors ISSUE 12's acceptance bar: killing a
replica mid-stream completes every in-flight request with a token stream
**bitwise identical** to an unkilled twin run — greedy AND sampled — and
``on_token`` never refires a token the client already has.  The router
passes each request's ORIGINAL sampling key to the survivor together
with ``replay_tokens=<delivered>``, so the continuation resamples the
exact per-token ``fold_in`` stream the dead replica would have produced;
``replay_parity_mismatch`` and ``serving_fleet_parity_mismatch`` staying
at zero proves it token by token.

Determinism: replicas are built with ``start=False`` and ticked by hand,
and the router with ``start_monitor=False`` so its monitor poll
(`_poll_once`) is a scripted step too — kill ordering is exact, not a
race the test hopes to win.
"""
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.engine import fault
from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_training_tpu.serving.batcher import OverloadedError
from pytorch_distributed_training_tpu.serving.fleet import ServingFleet
from pytorch_distributed_training_tpu.serving.metrics import (
    ServingMetrics,
    aggregate_snapshots,
)
from pytorch_distributed_training_tpu.serving.router import (
    FleetDownError,
    FleetRouter,
    ReplicaDownError,
)
from pytorch_distributed_training_tpu.serving.scheduler import ContinuousScheduler
from pytorch_distributed_training_tpu.telemetry.registry import get_registry

VOCAB = 61


def small_lm(**kwargs):
    return TransformerLM(
        vocab_size=VOCAB, max_len=32, embed_dim=32, depth=2, num_heads=4, **kwargs
    )


@pytest.fixture(scope="module")
def lm_and_params():
    model = small_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _prompts(seed=3, lens=(6, 5, 7, 6)):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, VOCAB, ln).astype(np.int32) for ln in lens]


def _mk_replica(model, params, replica_id, **kw):
    defaults = dict(
        slots=4, block_size=4, num_blocks=16, batch_buckets=[4],
        seq_buckets=[8], max_new_tokens=8, temperature=0.0, eos_id=None,
        prefix_cache=False, start=False, replica_id=replica_id,
    )
    defaults.update(kw)
    return ContinuousScheduler(model, params, **defaults)


def _mk_router(replicas, base, **kw):
    defaults = dict(
        base_rng=base, heartbeat_timeout_s=None, start_monitor=False,
    )
    defaults.update(kw)
    return FleetRouter(replicas, **defaults)


def _twin_streams(model, params, prompts, base, **sched_kw):
    """What an unkilled single replica produces for the same keys the
    router hands out (``fold_in(base, submission_ordinal)``)."""
    sched = _mk_replica(model, params, 9, **sched_kw)
    futs = [
        sched.submit(p, rng=jax.random.fold_in(base, i))
        for i, p in enumerate(prompts)
    ]
    n = 0
    while any(not f.done() for f in futs):
        sched.tick()
        n += 1
        assert n < 300, "twin run did not converge"
    out = [list(map(int, f.result()["tokens"])) for f in futs]
    sched.close()
    return out


def _drive(scheds, futs, limit=300):
    n = 0
    while any(not f.done() for f in futs):
        for s in scheds:
            s.tick()
        n += 1
        assert n < limit, "fleet run did not converge"


def _placements(router):
    with router._lock:
        return {
            i: [a.replica_idx for a in fr.assignments]
            for i, fr in enumerate(router._outstanding)
        }


# --------------------------------------------------------------------- #
# the tentpole oracle: mid-stream replica death, bitwise-equal completion


@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_failover_token_identity(lm_and_params, temperature):
    model, params = lm_and_params
    prompts = _prompts()
    base = jax.random.PRNGKey(42)
    fault.reset_counters()
    expected = _twin_streams(model, params, prompts, base,
                             temperature=temperature)

    fault.reset_counters()
    r0 = _mk_replica(model, params, 0, temperature=temperature)
    r1 = _mk_replica(model, params, 1, temperature=temperature)
    router = _mk_router([r0, r1], base)
    streams = {i: [] for i in range(len(prompts))}
    futs = [
        router.submit(p, on_token=lambda t, i=i: streams[i].append(int(t)))
        for i, p in enumerate(prompts)
    ]
    # least-loaded placement alternates over equally-idle replicas, so
    # both replicas hold in-flight work when one dies
    placed = _placements(router)
    assert {idx for a in placed.values() for idx in a} == {0, 1}

    for _ in range(3):  # mid-stream: a few tokens delivered everywhere
        r0.tick()
        r1.tick()
    assert all(0 < len(s) < len(expected[i]) for i, s in streams.items())

    r0.hard_kill(ReplicaDownError("chaos: replica 0 dies mid-stream"))
    r0.tick()            # scheduler thread processes the death
    router._poll_once()  # monitor dispatches failovers onto the survivor
    _drive([r1], futs)

    results = [list(map(int, f.result()["tokens"])) for f in futs]
    router.shutdown()
    r1.close()
    r0.close()
    assert results == expected
    # on_token never refired: each stream is exactly the result, in order
    assert [streams[i] for i in range(len(prompts))] == expected
    c = fault.counters()
    assert c.get("serving_fleet_failovers", 0) >= 1
    assert c.get("serving_fleet_replicas_down") == 1
    assert c.get("serving_fleet_parity_mismatch", 0) == 0
    assert c.get("replay_parity_mismatch", 0) == 0


def test_replica_down_injector_fires_failover(lm_and_params):
    """``replica_down@P[:R]`` keys on the router's poll index and kills
    exactly replica R; the kind-menu grammar drives the same failover
    path as a real death."""
    model, params = lm_and_params
    prompts = _prompts(seed=5, lens=(6, 6))
    base = jax.random.PRNGKey(7)
    fault.reset_counters()
    expected = _twin_streams(model, params, prompts, base)

    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = _mk_router([r0, r1], base)
    fault.install("replica_down@2:0")
    try:
        futs = [router.submit(p) for p in prompts]
        r0.tick()
        r1.tick()
        router._poll_once()  # poll 1: no fault yet
        router._poll_once()  # poll 2: hard-kills replica 0
        r0.tick()            # death processed; failover enqueued
        router._poll_once()  # poll 3: failover dispatched to replica 1
        _drive([r1], futs)
        results = [list(map(int, f.result()["tokens"])) for f in futs]
    finally:
        fault.install(None)
        router.shutdown()
        r1.close()
        r0.close()
    assert results == expected
    c = fault.counters()
    assert c.get("injected_replica_downs") == 1
    assert c.get("serving_fleet_replicas_down") == 1
    assert c.get("serving_fleet_parity_mismatch", 0) == 0


def test_heartbeat_staleness_marks_down_and_fails_over(
        lm_and_params, tmp_path):
    """A replica that stops beating (wedged in a device call — no Python
    progress, so no in-process signal) is detected from OUTSIDE via its
    heartbeat file's age and its requests fail over."""
    model, params = lm_and_params
    prompts = _prompts(seed=11, lens=(6, 6))
    base = jax.random.PRNGKey(13)
    fault.reset_counters()
    expected = _twin_streams(model, params, prompts, base)

    fault.reset_counters()
    hb = str(tmp_path / "r0.json")
    r0 = _mk_replica(model, params, 0, heartbeat_path=hb,
                     heartbeat_interval_s=0.01)
    r1 = _mk_replica(model, params, 1)
    # warm both replicas so the timed phase below measures ticks, not
    # first-call XLA compiles (a cold prefill takes longer than the
    # staleness budget and would trip the detector "early")
    for rep in (r0, r1):
        w = rep.submit(np.array([3, 4, 5, 6, 7], np.int32))
        _drive([rep], [w])
        w.result()
    router = _mk_router([r0, r1], base, heartbeat_timeout_s=0.2)
    r0.tick()  # fresh beat (r1's warmup compile aged the last one)
    futs = [router.submit(p) for p in prompts]
    assert {a for p in _placements(router).values() for a in p} == {0, 1}
    r0.tick()  # generates a little
    r1.tick()
    router._poll_once()
    assert not router.health()["replicas"][0]["heartbeat_stale"]
    # r0 now wedges: no more ticks, no more beats
    time.sleep(0.3)
    assert router._is_stale(r0)
    router._poll_once()  # staleness sweep marks it down + fails over
    _drive([r1], futs)
    results = [list(map(int, f.result()["tokens"])) for f in futs]
    health = router.health()
    router.shutdown()
    r1.close()
    r0.close()
    assert results == expected
    assert health["replicas"][0]["routed_down"] is True
    assert health["ready"] is True  # the survivor keeps the fleet up
    c = fault.counters()
    assert c.get("serving_fleet_replicas_down") == 1
    assert c.get("serving_fleet_failovers", 0) >= 1
    assert c.get("serving_fleet_parity_mismatch", 0) == 0


@pytest.mark.chaos
def test_serve_hang_liveness_from_heartbeat_age(lm_and_params, tmp_path):
    """Satellite regression: ``health()`` reports liveness from the
    wall-clock age of the last tick/beat, so a replica hung INSIDE a
    tick (``serve_hang`` — the thread is in time.sleep, exactly like a
    wedged device call) goes ``live: False`` while hung and recovers
    after."""
    model, params = lm_and_params
    fault.reset_counters()
    sched = _mk_replica(
        model, params, 0, start=True,
        heartbeat_path=str(tmp_path / "hb.json"),
        heartbeat_interval_s=0.02, liveness_timeout_s=0.25,
    )
    try:
        sched.submit(np.array([3, 4, 5, 6, 7], np.int32)).result(timeout=120)
        assert sched.health()["live"] is True
        # the tick counter kept running through the warmup; wedge the
        # SECOND tick from now (the first admits, so the hang catches the
        # request mid-decode)
        fault.install(f"serve_hang@{sched._tick_no + 2}:1.2")
        fut = sched.submit(np.array([7, 6, 5, 4, 3], np.int32))
        deadline = time.monotonic() + 5.0
        saw_stalled = False
        while time.monotonic() < deadline:
            h = sched.health()
            if h["stalled"]:
                saw_stalled = True
                assert h["live"] is False and h["ready"] is False
                break
            time.sleep(0.02)
        assert saw_stalled, "liveness never flipped during the hang"
        # the hang ends; the request completes and liveness recovers
        fut.result(timeout=30)
        assert sched.health()["live"] is True
    finally:
        fault.install(None)
        sched.close()
    assert fault.counters().get("injected_serve_hangs") == 1


def test_fleet_fault_kinds_parse_and_are_one_shot():
    """Grammar pin for the fleet kinds: ``replica_down`` takes a replica
    index (default 0), ``replica_hang`` takes seconds (default 1.0), and
    both are one-shot like the rest of the ``serve_*`` family."""
    inj = fault.FaultInjector(
        "replica_down@3:1;replica_hang@2:0.5;replica_down@7"
    )
    assert inj.take("replica_hang", 2) == 0.5
    assert inj.take("replica_down", 3) == 1.0
    assert inj.take("replica_down", 3) is None  # one-shot
    assert inj.take("replica_down", 7) == 0.0  # default replica index
    inj2 = fault.FaultInjector("replica_hang@4")
    assert inj2.take("replica_hang", 4) == 1.0  # default seconds


# --------------------------------------------------------------------- #
# placement


def test_affinity_routes_shared_prefix_to_one_replica(lm_and_params):
    """Requests sharing their first KV block land on ONE replica, and
    that replica's prefix cache actually hits (the gauge the satellite
    exports goes positive)."""
    model, params = lm_and_params
    base = jax.random.PRNGKey(21)
    fault.reset_counters()
    get_registry().gauge("serving_r0_prefix_hit_rate").set(0.0)
    get_registry().gauge("serving_r1_prefix_hit_rate").set(0.0)
    r0 = _mk_replica(model, params, 0, prefix_cache=True)
    r1 = _mk_replica(model, params, 1, prefix_cache=True)
    router = _mk_router([r0, r1], base)
    shared = np.array([9, 8, 7, 6], np.int32)  # one full block
    group = [np.concatenate([shared, [i + 2, i + 3]]).astype(np.int32)
             for i in range(3)]
    # the first group member populates the owner's prefix cache...
    first = router.submit(group[0])
    owners = {a for assigned in _placements(router).values() for a in assigned}
    assert len(owners) == 1
    owner = owners.pop()
    _drive([r0, r1], [first])
    first.result()
    # ...and the rest stick to the same replica and HIT that cache
    futs = [router.submit(p) for p in group[1:]]
    placed = _placements(router)
    assert all(a == [owner] for a in placed.values()), placed
    _drive([r0, r1], futs)
    for f in futs:
        f.result()
    hit_rate = get_registry().gauge(f"serving_r{owner}_prefix_hit_rate").value
    router.shutdown()
    r0.close()
    r1.close()
    assert hit_rate > 0.0
    assert fault.counters().get("serving_fleet_affinity_hits", 0) >= 2


def test_placement_skips_down_replica_and_fleet_down(lm_and_params):
    model, params = lm_and_params
    base = jax.random.PRNGKey(23)
    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = _mk_router([r0, r1], base)
    r0.hard_kill(ReplicaDownError("dead"))
    r0.tick()
    router._poll_once()  # liveness sweep routes replica 0 out
    futs = [router.submit(p) for p in _prompts(seed=31, lens=(6, 6))]
    placed = _placements(router)
    assert all(a == [1] for a in placed.values()), placed
    _drive([r1], futs)
    for f in futs:
        f.result()
    # the whole fleet down -> submit fails loudly, not silently queued
    r1.hard_kill(ReplicaDownError("dead too"))
    r1.tick()
    router._poll_once()
    with pytest.raises(FleetDownError):
        router.submit(np.array([2, 3, 4, 5, 6], np.int32))
    router.shutdown()
    r0.close()
    r1.close()


def test_fleet_backpressure_sheds_at_router(lm_and_params):
    model, params = lm_and_params
    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    router = _mk_router([r0], jax.random.PRNGKey(1), max_backlog=2)
    p = np.array([2, 3, 4, 5, 6], np.int32)
    futs = [router.submit(p) for _ in range(2)]
    with pytest.raises(OverloadedError):
        router.submit(p)
    _drive([r0], futs)
    for f in futs:
        f.result()
    router.shutdown()
    r0.close()
    assert fault.counters().get("serving_fleet_sheds") == 1


# --------------------------------------------------------------------- #
# hedging


def test_hedge_first_writer_wins(lm_and_params):
    """A straggling request gets a duplicate dispatch; both replicas
    deliver, the per-token dedupe keeps the stream single and ordered,
    and the result matches the unhedged twin bitwise."""
    model, params = lm_and_params
    prompts = _prompts(seed=17, lens=(6,))
    base = jax.random.PRNGKey(19)
    fault.reset_counters()
    expected = _twin_streams(model, params, prompts, base,
                             temperature=1.0)

    fault.reset_counters()
    r0 = _mk_replica(model, params, 0, temperature=1.0)
    r1 = _mk_replica(model, params, 1, temperature=1.0)
    router = _mk_router([r0, r1], base, hedge_ms=50.0)
    stream = []
    fut = router.submit(prompts[0], on_token=lambda t: stream.append(int(t)))
    r0.tick()
    r0.tick()  # partial progress on the primary...
    with router._lock:
        freq = router._outstanding[0]
        freq.last_progress -= 10.0  # ...then it stalls (simulated)
    router._poll_once()
    with router._lock:
        assert len(freq.assignments) == 2, "hedge was not dispatched"
    # BOTH replicas race the remainder; every token index is delivered
    # exactly once, first writer wins
    _drive([r0, r1], [fut])
    result = list(map(int, fut.result()["tokens"]))
    router.shutdown()
    r0.close()
    r1.close()
    assert result == expected[0]
    assert stream == expected[0]
    c = fault.counters()
    assert c.get("serving_fleet_hedges") == 1
    assert c.get("serving_fleet_parity_mismatch", 0) == 0
    assert c.get("replay_parity_mismatch", 0) == 0


# --------------------------------------------------------------------- #
# fleet lifecycle


def test_fleet_drain_concurrent_and_late_submit_raises(lm_and_params):
    model, params = lm_and_params
    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = _mk_router([r0, r1], jax.random.PRNGKey(2))
    fleet = ServingFleet([r0, r1], router)
    futs = [fleet.submit(p) for p in _prompts(seed=37, lens=(6, 5, 7, 6))]
    ms = fleet.drain(deadline_ms=30_000)
    assert ms >= 0.0
    # drain finished the in-flight work rather than failing it
    for f in futs:
        assert len(f.result(timeout=1)["tokens"]) > 0
    for rep in (r0, r1):
        assert rep.health()["closed"] is True
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(np.array([2, 3, 4, 5, 6], np.int32))
    assert fleet.drain() == 0.0  # idempotent
    fleet.close()


def test_fleet_sigterm_routes_to_drain(lm_and_params):
    model, params = lm_and_params
    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    router = _mk_router([r0], jax.random.PRNGKey(3))
    fleet = ServingFleet([r0], router)
    fut = fleet.submit(np.array([5, 6, 7, 8, 9], np.int32))
    prev = signal.getsignal(signal.SIGTERM)
    try:
        fleet.install_drain_handler()
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler) and handler is not prev
        handler(signal.SIGTERM, None)  # what the kernel would deliver
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not r0.health()["closed"]:
            time.sleep(0.01)
        assert r0.health()["closed"] is True
        assert len(fut.result(timeout=1)["tokens"]) > 0
    finally:
        signal.signal(signal.SIGTERM, prev)
        fleet.close()


# --------------------------------------------------------------------- #
# metrics namespacing + aggregation (satellite)


def test_metrics_namespacing_and_fleet_aggregate(lm_and_params):
    assert ServingMetrics(3).global_name("sheds") == "serving_r3_sheds"
    assert ServingMetrics().global_name("sheds") == "serving_sheds"

    model, params = lm_and_params
    fault.reset_counters()
    base = jax.random.PRNGKey(29)
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = _mk_router([r0, r1], base)
    fleet = ServingFleet([r0, r1], router)
    futs = [fleet.submit(p) for p in _prompts(seed=41, lens=(6, 6))]
    _drive([r0, r1], futs)
    for f in futs:
        f.result()
    snap = fleet.snapshot()
    fleet.close()
    assert set(snap["replicas"]) == {"r0", "r1"}
    agg = snap["fleet"]
    assert agg["replicas"] == 2
    # per-replica request counters SUM across the fleet
    assert agg["requests"] == (
        snap["replicas"]["r0"]["requests"] + snap["replicas"]["r1"]["requests"]
    )
    # tail latency takes the MAX (a fleet p99 is no better than its
    # worst replica)
    assert agg["latency_ms_p99"] == max(
        snap["replicas"]["r0"]["latency_ms_p99"],
        snap["replicas"]["r1"]["latency_ms_p99"],
    )
    # namespaced counters landed in the shared registry without colliding
    c = fault.counters()
    assert c.get("serving_r0_retired", 0) >= 1
    assert c.get("serving_r1_retired", 0) >= 1


# --------------------------------------------------------------------- #
# elastic scale-down: drain-preserved parity (ISSUE 18 tentpole oracle)


def test_scale_down_drains_in_flight_requests_token_identical(lm_and_params):
    """Retiring a replica mid-stream (the autoscaler's scale-down path)
    completes its in-flight requests with token streams bitwise equal to
    an unscaled twin: retirement only removes the replica from
    placement — nothing is killed, failed over, or replayed."""
    model, params = lm_and_params
    prompts = _prompts(seed=23)
    base = jax.random.PRNGKey(31)
    fault.reset_counters()
    expected = _twin_streams(model, params, prompts, base)

    fault.reset_counters()
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = _mk_router([r0, r1], base)
    streams = {i: [] for i in range(len(prompts))}
    futs = [
        router.submit(p, on_token=lambda t, i=i: streams[i].append(int(t)))
        for i, p in enumerate(prompts)
    ]
    placed = _placements(router)
    assert {idx for a in placed.values() for idx in a} == {0, 1}
    on_retiree = [
        i for i, a in placed.items() if any(idx == 1 for idx in a)
    ]

    for _ in range(3):  # mid-stream on both replicas
        r0.tick()
        r1.tick()
    assert all(0 < len(s) < len(expected[i]) for i, s in streams.items())

    router.retire_replica(1)
    assert router.live_indices() == [0]
    # new work no longer lands on the retiree...
    tail = router.submit(prompts[0])
    assert all(
        a.replica_idx == 0
        for a in router._outstanding[-1].assignments
    )
    # ...while its in-flight requests keep ticking to completion (what
    # fleet.remove_replica's drain step does, hand-driven here)
    _drive([r0, r1], futs + [tail])

    results = [list(map(int, f.result()["tokens"])) for f in futs]
    router.shutdown()
    r1.close()
    r0.close()
    assert on_retiree, "placement never used the retiree; oracle is vacuous"
    assert results == expected
    assert [streams[i] for i in range(len(prompts))] == expected
    c = fault.counters()
    assert c.get("serving_fleet_replicas_retired") == 1
    # drain is not death: no failover, no replay, no parity repair ran
    assert c.get("serving_fleet_failovers", 0) == 0
    assert c.get("serving_fleet_replicas_down", 0) == 0
    assert c.get("serving_fleet_parity_mismatch", 0) == 0
    assert c.get("replay_parity_mismatch", 0) == 0
