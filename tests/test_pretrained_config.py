"""model.pretrained: config-driven pretrained-weight ingestion.

The user-facing form of the reference's TORCH_HOME model-zoo weights
(/root/reference/train.sh:2, README.md:4): a torch ``state_dict`` checkpoint
path in the ``model:`` section initializes the run from ported weights.
The port machinery itself is pinned by tests/test_torch_port(_lm).py; these
tests pin the CONFIG wiring — the Runner's initial state must equal the
ported variables (and its eval step must reproduce torch eval logits), and
mismatches must fail with descriptive errors, not part-load.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from test_torch_port import (
    TorchBasicBlock,
    TorchResNet,
    _randomize_running_stats,
)
from test_torch_port_lm import DEPTH, EMBED, HEADS, MAXLEN, VOCAB, _randomized_twin

from pytorch_distributed_training_tpu.engine import Runner


class _CaptureRunner(Runner):
    """Stops right before the training loop: captures the constructed state."""

    def _train_loop(self, iter_generator, train_cfg):
        self.captured = self.state


def _image_cfg(tmp_path, ckpt, n_classes=10, **model_extra):
    return {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": n_classes,
            "image_size": 64,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {
                "name": "SGD", "lr": 0.05, "weight_decay": 1.0e-4, "momentum": 0.9,
            },
            "lr_schedule": {"name": "multi_step", "milestones": [4], "gamma": 0.1},
            "train_iters": 2,
            "print_interval": 1,
            "val_interval": 2,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": False,
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ResNet18", "pretrained": str(ckpt), **model_extra},
    }


def _run_captured(cfg):
    runner = _CaptureRunner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9917",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    return runner


def test_pretrained_resnet_initial_eval_matches_torch(tmp_path):
    """Config-driven run starts at the ported weights: the Runner's own eval
    step on the pretrained state reproduces torch eval logits."""
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=10)
    _randomize_running_stats(tmodel, seed=1)
    tmodel.eval()
    ckpt = tmp_path / "resnet18.pt"
    torch.save(tmodel.state_dict(), ckpt)

    runner = _run_captured(_image_cfg(tmp_path, ckpt))
    state = runner.captured

    rng = np.random.default_rng(5)
    img = rng.standard_normal((8, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
    out = np.asarray(
        runner.model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(img),
            train=False,
        )
    )
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_pretrained_lm_params_match_direct_port(tmp_path):
    from pytorch_distributed_training_tpu.models.torch_port import (
        import_torch_lm_state_dict,
    )

    tm = _randomized_twin()
    ckpt = tmp_path / "lm.pt"
    torch.save(tm.state_dict(), ckpt)

    cfg = {
        "dataset": {
            "name": "synthetic_text",
            "root": str(tmp_path),
            "n_classes": VOCAB,
            "n_samples": 64,
            "seq_len": MAXLEN,
        },
        "training": {
            "optimizer": {"name": "AdamW", "lr": 3.0e-4, "weight_decay": 0.1},
            "lr_schedule": {"name": "cosine", "total_iters": 100},
            "train_iters": 2,
            "print_interval": 1,
            "val_interval": 2,
            "batch_size": 8,
            "num_workers": 2,
            "sync_bn": False,
        },
        "validation": {"batch_size": 8, "num_workers": 2},
        "model": {
            "name": "TransformerLM",
            "pretrained": str(ckpt),
            "embed_dim": EMBED,
            "depth": DEPTH,
            "num_heads": HEADS,
            "max_len": MAXLEN,
        },
    }
    runner = _run_captured(cfg)
    state = runner.captured

    template = jax.tree.map(np.asarray, state.params)
    expected = import_torch_lm_state_dict(template, tm.state_dict())
    got_flat = jax.tree_util.tree_leaves_with_path(
        jax.tree.map(np.asarray, state.params)
    )
    exp_flat = dict(
        (jax.tree_util.keystr(p), leaf)
        for p, leaf in jax.tree_util.tree_leaves_with_path(expected)
    )
    assert got_flat, "empty params"
    for path, leaf in got_flat:
        np.testing.assert_array_equal(leaf, exp_flat[jax.tree_util.keystr(path)])


def test_pretrained_missing_file_raises(tmp_path):
    cfg = _image_cfg(tmp_path, tmp_path / "nope.pt")
    with pytest.raises(FileNotFoundError, match="model.pretrained"):
        _run_captured(cfg)


def test_pretrained_wrong_topology_raises(tmp_path):
    """A ResNet-34-shaped dict into a ResNet-18 config: descriptive failure,
    not a silent part-load."""
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [3, 4, 6, 3], num_classes=10)
    ckpt = tmp_path / "resnet34.pt"
    torch.save(tmodel.state_dict(), ckpt)
    with pytest.raises(KeyError, match="not consumed|missing"):
        _run_captured(_image_cfg(tmp_path, ckpt))


def test_pretrained_wrong_classes_raises(tmp_path):
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=7)
    ckpt = tmp_path / "resnet18c7.pt"
    torch.save(tmodel.state_dict(), ckpt)
    with pytest.raises(ValueError, match="shape mismatch"):
        _run_captured(_image_cfg(tmp_path, ckpt, n_classes=10))


def test_pretrained_vit_wrong_dict_raises(tmp_path):
    """ViT is a supported pretrained family (round 4,
    tests/test_torch_port_vit.py pins the logit parity); a non-ViT dict
    must still fail loudly with the missing torchvision key."""
    ckpt = tmp_path / "any.pt"
    torch.save({}, ckpt)
    cfg = _image_cfg(tmp_path, ckpt)
    cfg["model"]["name"] = "ViT-Ti16"
    with pytest.raises(KeyError, match="conv_proj"):
        _run_captured(cfg)
