"""Subprocess worker for the multi-host execution test.

Runs the REAL multi-host path end to end in one OS process per "host":
``jax.distributed.initialize`` over the coordination service (the
reference's ``dist.init_process_group`` rendezvous,
/root/reference/train_distributed.py:149-154), a global mesh spanning both
processes' virtual CPU devices, per-host ``DistributedShardSampler`` shards,
and ``jax.make_array_from_process_local_data`` batch assembly — the code
paths that single-process tests cannot reach.

Driven by tests/test_multihost.py via environment variables:
  MH_RANK           process id (0-based)
  MH_NUM_NODES      number of processes ("hosts")
  MH_PORT           coordinator port on 127.0.0.1 — or a comma-separated
                    candidate list; rank 0 probes them in order (bounded,
                    one attempt per candidate) and publishes the winner
                    through MH_PORT_FILE, so a bind collision with another
                    test run retries on the next candidate instead of dying
  MH_PORT_FILE      rendezvous file for the chosen port (required when
                    MH_PORT lists more than one candidate)
  MH_OUT            output JSON path (plus <MH_OUT>.npz for final params)
  MH_LOCAL_DEVICES  virtual CPU devices per process
  MH_BATCH_DIVISION training.batch_division value ("local" or "world")
  MH_ELASTIC        "1" arms training.elastic (heartbeat peer-loss layer)
  MH_HB_INTERVAL    elastic heartbeat interval seconds (default 0.1)
  MH_HB_TIMEOUT     elastic peer timeout seconds (default 0.75)

A diagnosed peer loss (engine.elastic.PeerLostError) is NOT a worker
failure: the survivor writes its JSON with the diagnosis + recovery
counters and exits 0 — the driving test asserts on that record.

The platform must be pinned to CPU *before* mesh construction because a
site-installed accelerator plugin may force ``jax_platforms`` to itself.
"""
import json
import os
import sys
import time

rank = int(os.environ["MH_RANK"])
num_nodes = int(os.environ["MH_NUM_NODES"])
out_path = os.environ["MH_OUT"]
local_devices = int(os.environ.get("MH_LOCAL_DEVICES", "4"))


def _choose_port(spec: str, rank: int) -> str:
    """Resolve the coordinator port from a candidate list (see MH_PORT)."""
    candidates = [c.strip() for c in spec.split(",") if c.strip()]
    port_file = os.environ.get("MH_PORT_FILE")
    if len(candidates) == 1 and not port_file:
        return candidates[0]  # legacy single-port path, no rendezvous file
    if not port_file:
        raise RuntimeError(
            "MH_PORT lists multiple candidates; set MH_PORT_FILE so "
            "non-zero ranks can learn which one rank 0 bound"
        )
    if rank == 0:
        import socket

        last_err = None
        for cand in candidates:  # bounded: one probe per candidate
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    s.bind(("127.0.0.1", int(cand)))
                finally:
                    s.close()
            except OSError as e:
                last_err = e
                continue
            tmp = port_file + ".tmp"
            with open(tmp, "w") as fp:
                fp.write(cand)
            os.replace(tmp, port_file)  # atomic publish
            return cand
        raise RuntimeError(
            f"no free coordinator port among {candidates}: {last_err}"
        )
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        try:
            with open(port_file) as fp:
                text = fp.read().strip()
        except OSError:
            text = ""
        if text:
            return text
        time.sleep(0.05)
    raise RuntimeError(
        f"rank {rank}: rank 0 never published a coordinator port to "
        f"{port_file} within 30s"
    )


port = _choose_port(os.environ["MH_PORT"], rank)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={local_devices}"
)
# Opt into the jax.shard_map compat graft (utils/jax_compat.py) BEFORE the
# package import installs it: this worker is by definition a CPU test
# harness on whatever JAX the dev image ships, and every assertion driven
# through it compares runs of the SAME compiled program against each other
# (multi-process vs single, interrupted vs oracle), so the pre-vma
# autodiff caveat — consistent-but-different gradients on multi-device
# meshes — cannot skew a verdict.  Inert on the grafted toolchain.
os.environ.setdefault("PDT_JAX_COMPAT", "1")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from pytorch_distributed_training_tpu.engine import (  # noqa: E402
    PeerLostError,
    Runner,
    fault,
)


class _RecordingTB:
    """Minimal SummaryWriter stand-in capturing every scalar write."""

    def __init__(self):
        self.scalars = []

    def add_scalar(self, tag, value, step):
        self.scalars.append((tag, float(value), int(step)))


class _RecordingRunner(Runner):
    """Runner that additionally records the per-iteration loss scalar, and
    can deliver a SIGTERM to ITSELF at a configured iteration (simulating a
    spot eviction landing on exactly one host — the multi-process
    preemption-agreement path, runner._globally_preempted)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.losses = []
        self._self_preempt_at = int(os.environ.get("MH_SELF_PREEMPT_AT", "-1"))
        self._self_preempt_rank = int(
            os.environ.get("MH_SELF_PREEMPT_RANK", "-1")
        )

    def train_iter(self, g_img, g_label):
        self.state, loss = self.train_step(self.state, g_img, g_label)
        self.losses.append(float(loss))
        self.scheduler.step()  # per-iteration, reference :299
        if (
            self.iter == self._self_preempt_at
            and self.current_rank == self._self_preempt_rank
        ):
            import signal

            os.kill(os.getpid(), signal.SIGTERM)


def main():
    task = os.environ.get("MH_TASK", "image")
    if task == "lm":
        # multi-process long-context path: token dataset + TransformerLM,
        # tokens sharded over the (data, sequence) axes across processes
        dataset = {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 128,
        }
        model = {"name": "TransformerLM", "embed_dim": 32, "depth": 2,
                 "num_heads": 4}
        extra = {"sequence_parallelism": int(os.environ.get("MH_SEQ_PAR", "1"))}
    else:
        dataset = {
            "name": "synthetic",
            "root": "/unused",
            "n_classes": 8,
            "image_size": 32,
            "n_samples": 128,
        }
        model = {"name": "ResNet18"}
        extra = {}
    ckpt_dir = os.environ.get("MH_CKPT_DIR")
    ckpt = (
        {
            "checkpoint": {
                "dir": ckpt_dir,
                # huge regular interval: only the preemption path (or the
                # final iteration) writes, so the test can attribute saves
                "interval": int(os.environ.get("MH_CKPT_INTERVAL", "100000")),
                "preemption_sync_interval": int(
                    os.environ.get("MH_PREEMPT_SYNC", "2")
                ),
            }
        }
        if ckpt_dir
        else {}
    )
    if os.environ.get("MH_ELASTIC") == "1":
        ckpt["elastic"] = {
            "enabled": True,
            "heartbeat_interval": float(os.environ.get("MH_HB_INTERVAL", "0.1")),
            "timeout": float(os.environ.get("MH_HB_TIMEOUT", "0.75")),
        }
    cfg = {
        "dataset": dataset,
        "training": {
            **ckpt,
            "optimizer": {
                "name": "SGD",
                # small lr: keeps the 4-step trajectory out of the chaotic
                # large-step regime so cross-topology float32 reduction-order
                # noise stays at tolerance scale instead of amplifying
                "lr": 0.001,
                "weight_decay": 1.0e-4,
                "momentum": 0.9,
            },
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": int(os.environ.get("MH_TRAIN_ITERS", "4")),
            "print_interval": 1,
            "val_interval": 100,  # is_val still fires on the last iter (p3)
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": task != "lm",
            "batch_division": os.environ.get("MH_BATCH_DIVISION", "world"),
            **extra,
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": model,
    }
    tb = _RecordingTB()
    runner = _RecordingRunner(
        num_nodes=num_nodes,
        rank=rank,
        seed=1029,
        dist_url=f"tcp://127.0.0.1:{port}",
        dist_backend="tpu",
        multiprocessing=False,
        logger_queue=None,
        global_cfg=cfg,
        tb_writer_constructor=lambda: tb,
    )
    try:
        runner()
    except PeerLostError as e:
        # the DIAGNOSED dead-peer outcome the elastic layer promises: record
        # it (plus the recovery counters — the emergency save already ran in
        # runner._on_peer_lost) and exit 0.  os._exit skips interpreter
        # teardown: jax.distributed shutdown barriers would hang against the
        # very peer whose death was just diagnosed.
        with open(out_path, "w") as fp:
            json.dump(
                {
                    "rank": rank,
                    "peer_lost": str(e),
                    "dead_ranks": list(getattr(e, "dead_ranks", ())),
                    "mid_step": bool(getattr(e, "mid_step", False)),
                    "losses": runner.losses,
                    "final_iter": runner.iter,
                    "counters": fault.counters(),
                },
                fp,
            )
            fp.flush()
            os.fsync(fp.fileno())
        os._exit(0)

    params = jax.tree.leaves(jax.tree.map(np.asarray, runner.state.params))
    np.savez(out_path + ".npz", **{f"p{i}": p for i, p in enumerate(params)})
    with open(out_path, "w") as fp:
        json.dump(
            {
                "rank": rank,
                "process_count": jax.process_count(),
                "world_size": runner.world_size,
                "global_batch": runner.global_batch,
                "losses": runner.losses,
                "final_iter": runner.iter,
                "eval": {t: v for t, v, _ in tb.scalars if t.startswith("eval/")},
                "counters": fault.counters(),
                "param_bytes_digest": __import__("hashlib").sha256(
                    b"".join(p.tobytes() for p in params)
                ).hexdigest(),
            },
            fp,
        )


if __name__ == "__main__":
    main()
