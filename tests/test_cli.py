"""CLI-level behavior that Runner-level tests cannot reach.

The crash path (reference train_distributed.py:77-86): a failure inside
the runner must log CRITICAL, delete ONLY the TensorBoard event directory
(the reference's rmtree bug deleted the whole log dir — we implement the
intent), keep the text log, stop the listener cleanly, and exit 0.
"""
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BAD_CFG = """\
dataset: {name: synthetic, root: /tmp/none, n_classes: 8, image_size: 32, n_samples: 64}
training:
    optimizer: {name: SGD, lr: 0.01, weight_decay: 1.0e-4, momentum: 0.9}
    lr_schedule: {name: multi_step, milestones: [6], gamma: 0.1}
    train_iters: 4
    print_interval: 2
    val_interval: 4
    batch_size: 16
    num_workers: 2
    sync_bn: True
validation: {batch_size: 16, num_workers: 2}
model: {name: NoSuchModel}
"""


def test_cli_crash_path_cleans_tb_only(tmp_path):
    cfg = tmp_path / "bad.yml"
    cfg.write_text(_BAD_CFG)
    log_dir = tmp_path / "run"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    )
    proc = subprocess.run(
        [
            sys.executable, os.path.join(_ROOT, "train_distributed.py"),
            "--num-nodes", "1", "--rank", "0",
            "--dist-backend", "tpu", "--dist-url", "tcp://127.0.0.1:9981",
            "--log-dir", str(log_dir), "--file-name-cfg", "bad",
            "--cfg-filepath", str(cfg), "--seed", "1",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    # reference behavior: handled crash, clean exit
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log_file = log_dir / "bad.log"
    assert log_file.exists()
    content = log_file.read_text()
    assert "CRITICAL" in content and "NoSuchModel" in content
    # only the TB event dir is removed; the text log survives
    assert not (log_dir / "tf-board-logs").exists()
