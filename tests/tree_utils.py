"""Shared pytree helpers for tests (single source of the path-key format)."""
import numpy as np

import jax


def flat_tree(tree, materialize=True):
    """Flatten to {path-string: leaf}; materialize=False keeps live arrays
    (with their shardings) instead of host numpy copies."""
    conv = np.asarray if materialize else (lambda x: x)
    return {
        "/".join(str(getattr(k, "key", k)) for k in path): conv(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }
