"""Checkpoint/resume: config-gated orbax save/restore of the full TrainState."""
import pytest
import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.engine import Runner
from pytorch_distributed_training_tpu.engine.checkpoint import Checkpointer


def _cfg(tmp_path, ckpt=True, train_iters=4):
    cfg = {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 4,
            "image_size": 16,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {"name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4, "momentum": 0.9},
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": train_iters,
            "print_interval": 10,
            "val_interval": 100,
            "batch_size": 16,
            "num_workers": 0,
            "sync_bn": True,
        },
        "validation": {"batch_size": 16, "num_workers": 0},
        "model": {"name": "ResNet18"},
    }
    if ckpt:
        cfg["training"]["checkpoint"] = {
            "dir": str(tmp_path / "ckpt"),
            "interval": 2,
            "resume": True,
        }
    return cfg


def _run(cfg):
    runner = Runner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9901",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    return runner


def test_from_config_gating(tmp_path):
    assert Checkpointer.from_config({}) is None
    assert Checkpointer.from_config({"checkpoint": {}}) is None
    ck = Checkpointer.from_config({"checkpoint": {"dir": str(tmp_path), "interval": 5}})
    assert ck is not None and ck.interval == 5
    ck.close()


def test_save_and_resume(tmp_path):
    cfg = _cfg(tmp_path, train_iters=4)
    r1 = _run(cfg)
    params_after_4 = jax.tree.map(np.asarray, r1.state.params)
    assert int(r1.state.step) == 4

    # Second run with train_iters extended: must resume from iter 4 (saved at
    # iters 1 and 3 via interval=2 -> latest step 3, resume at 4), not restart.
    cfg2 = _cfg(tmp_path, train_iters=6)
    r2 = _run(cfg2)
    assert int(r2.state.step) == 6
    # resumed state continued from the first run's params (not re-initialized)
    leaf1 = jax.tree.leaves(params_after_4)[0]
    leaf2 = jax.tree.leaves(jax.tree.map(np.asarray, r2.state.params))[0]
    assert not np.allclose(leaf1, leaf2)  # moved past iter-4 params

    # Third run with same train_iters=6: nothing left to do, state preserved
    cfg3 = _cfg(tmp_path, train_iters=6)
    r3 = _run(cfg3)
    assert int(r3.state.step) == 6
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(r3.state.params)[0]), leaf2, rtol=0, atol=0
    )


def test_resume_false_populated_dir_rejected(tmp_path):
    """orbax never overwrites a step; fresh-run-into-populated-dir must fail fast."""
    import pytest

    _run(_cfg(tmp_path, train_iters=2))  # populates ckpt dir (step 1)
    cfg = _cfg(tmp_path, train_iters=2)
    cfg["training"]["checkpoint"]["resume"] = False
    with pytest.raises(Exception) as exc_info:
        _run(cfg)
    assert "resume is False" in str(exc_info.value)


def test_resume_bit_exact_vs_straight_run(tmp_path):
    """4 iters straight == 2 iters + checkpoint + resume 2 more (bit-exact)."""
    straight = _run(_cfg(tmp_path / "a", ckpt=False, train_iters=4))

    cfg_b = _cfg(tmp_path / "b", train_iters=2)
    cfg_b["training"]["checkpoint"]["interval"] = 2
    _run(cfg_b)
    cfg_b2 = _cfg(tmp_path / "b", train_iters=4)
    cfg_b2["training"]["checkpoint"]["interval"] = 2
    resumed = _run(cfg_b2)

    a = jax.tree.map(np.asarray, straight.state.params)
    b = jax.tree.map(np.asarray, resumed.state.params)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(x, y)


@pytest.fixture
def one_device_graft(monkeypatch):
    """``jax.shard_map`` compat-grafted for this test only, pinned to a
    ONE-device mesh — collectives over a size-1 axis are identity, so the
    pre-vma graft's autodiff caveat (utils/jax_compat.py) does not apply
    and the real train step runs bit-deterministically on vanilla JAX."""
    from pytorch_distributed_training_tpu.engine import paths
    from pytorch_distributed_training_tpu.parallel import make_mesh

    if not hasattr(jax, "shard_map"):
        from pytorch_distributed_training_tpu.utils import jax_compat

        monkeypatch.setenv("PDT_JAX_COMPAT", "1")
        jax_compat.install()
        wrapper = jax.shard_map
        del jax.shard_map
        monkeypatch.setattr(jax, "shard_map", wrapper, raising=False)
    mesh = make_mesh(jax.devices()[:1])
    monkeypatch.setattr(paths, "make_mesh", lambda *a, **kw: mesh)
    return mesh


class _BatchHashingRunner(Runner):
    """Records a digest of every training batch the step consumes — the
    observable the mid-epoch-resume contract is stated in."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.batch_hashes = []

    def train_iter(self, g_img, g_label):
        import hashlib

        h = hashlib.sha256()
        h.update(np.asarray(g_img).tobytes())
        h.update(np.asarray(g_label).tobytes())
        self.batch_hashes.append(h.hexdigest())
        super().train_iter(g_img, g_label)


def _run_hashing(cfg):
    runner = _BatchHashingRunner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9903",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    return runner


@pytest.mark.slow
def test_mid_epoch_resume_batch_sequence_bit_exact(tmp_path, one_device_graft):
    """Interrupt at iteration 2 of a 4-batch epoch and resume: the resumed
    run must consume EXACTLY the batches (bitwise) the uninterrupted run
    would have — pinned on the batch digests, not just the final params —
    and the checkpoint must carry the (epoch, batch_in_epoch) sidecar the
    resume used."""
    import json as _json
    import os

    straight = _run_hashing(_cfg(tmp_path / "a", ckpt=False, train_iters=6))
    assert len(straight.batch_hashes) == 6  # 64 samples/16 = 4 per epoch

    cfg_b = _cfg(tmp_path / "b", train_iters=2)
    first = _run_hashing(cfg_b)
    assert first.batch_hashes == straight.batch_hashes[:2]

    # the interval-2 save at step 1 wrote the pipeline sidecar: 2 batches
    # of epoch 0 consumed — a MID-epoch position
    sidecar = os.path.join(str(tmp_path / "b" / "ckpt"), "pipeline_1.json")
    assert os.path.exists(sidecar), "pipeline sidecar missing"
    with open(sidecar) as fp:
        extras = _json.load(fp)
    assert extras["epoch"] == 0 and extras["batch_in_epoch"] == 2
    assert extras["batches_per_epoch"] == 4

    resumed = _run_hashing(_cfg(tmp_path / "b", train_iters=6))
    assert resumed.iter == 6
    # the resumed stream picked up at epoch 0, batch 2 — bit-identical
    assert resumed.batch_hashes == straight.batch_hashes[2:]


def test_emergency_checkpoint_roundtrip_and_precedence(tmp_path):
    """save_emergency/restore_latest: a survivor's local dump of fully-
    replicated state restores exactly (values + extras), is preferred over
    OLDER orbax steps, and yields to NEWER ones; non-replicated state is
    rejected with a diagnosis instead of silently saving one shard."""
    from pytorch_distributed_training_tpu.engine import TrainState, fault
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import replicated_sharding
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh

    opt = SGD(lr=0.1, momentum=0.9)
    mesh = make_mesh()

    def make_state(fill):
        params = {"w": jnp.full((8, 4), float(fill)), "b": jnp.full((4,), float(fill))}
        state = TrainState(
            params=params, batch_stats={}, opt_state=opt.init(params)
        )
        return jax.device_put(state, replicated_sharding(mesh))

    fault.reset_counters()
    ck = Checkpointer(str(tmp_path / "c"), interval=1)
    ck.save(3, make_state(3.0))
    ck.wait()

    extras = {"epoch": 1, "batch_in_epoch": 2, "batches_per_epoch": 4}
    ck.save_emergency(4, make_state(4.0), extras=extras)
    assert ck.latest_emergency() == 4
    assert ck.read_extras(4)["batch_in_epoch"] == 2

    # newer than orbax step 3: the emergency dump wins
    restored, next_iter = ck.restore_latest(make_state(0.0))
    assert next_iter == 5
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.full((8, 4), 4.0)
    )
    assert fault.counters().get("elastic_restores") == 1

    # an orbax step NEWER than the emergency takes precedence again
    ck.save(9, make_state(9.0))
    ck.wait()
    restored2, next_iter2 = ck.restore_latest(make_state(0.0))
    assert next_iter2 == 10
    np.testing.assert_array_equal(
        np.asarray(restored2.params["w"]), np.full((8, 4), 9.0)
    )

    # sharded (non-replicated) state: a lone survivor holds one shard only
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded = jax.device_put(
        jnp.arange(32.0).reshape(8, 4), NamedSharding(mesh, P("data"))
    )
    bad = TrainState(
        params={"w": sharded}, batch_stats={}, opt_state=opt.init({"w": sharded})
    )
    with pytest.raises(ValueError, match="survivor"):
        ck.save_emergency(11, bad)
    ck.close()


def test_preemption_guard_restores_handlers():
    import signal

    from pytorch_distributed_training_tpu.engine.preemption import PreemptionGuard

    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert signal.getsignal(signal.SIGTERM) is not before
        assert not g.triggered
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_checkpoints_current_iter_and_resumes(tmp_path, monkeypatch):
    """SIGTERM mid-run (engine/preemption.py): the loop must save a
    checkpoint at the CURRENT iteration — not an interval boundary — exit
    cleanly, and a relaunch must resume past it to completion.

    The signal is raised from inside the third train_iter (so the guard is
    installed and the timing is deterministic — a wall-clock timer can fire
    during setup, before the guard exists, and kill the process)."""
    import os
    import signal

    cfg = _cfg(tmp_path, train_iters=400)
    # a huge interval isolates the preemption save from the periodic one
    cfg["training"]["checkpoint"]["interval"] = 10_000

    orig = Runner.train_iter
    calls = {"n": 0}

    def train_then_preempt(self, *args):
        orig(self, *args)
        calls["n"] += 1
        if calls["n"] == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    monkeypatch.setattr(Runner, "train_iter", train_then_preempt)
    runner = _run(cfg)
    monkeypatch.setattr(Runner, "train_iter", orig)
    stopped_at = runner.iter
    assert stopped_at == 2  # preempted during the 3rd iteration (0-indexed)
    ck = Checkpointer(str(tmp_path / "ckpt"))
    assert ck.latest() == stopped_at
    ck.close()

    # relaunch with a few more iters: resumes from the preemption save
    cfg2 = _cfg(tmp_path, train_iters=stopped_at + 3)
    cfg2["training"]["checkpoint"]["interval"] = 10_000
    runner2 = _run(cfg2)
    assert runner2.iter == stopped_at + 3


def test_preemption_opt_out(tmp_path):
    """checkpoint.preemption: False keeps the reference's fail-fast
    behavior — no guard is installed."""
    cfg = _cfg(tmp_path, train_iters=2)
    cfg["training"]["checkpoint"]["preemption"] = False
    runner = _run(cfg)
    assert runner._preempt is None
    assert runner.iter == 2


def test_restore_converts_pp_layout_both_ways(tmp_path):
    """A checkpoint written under pipeline_parallelism (stacked
    {blocks, shared} params + mirrored optimizer moments) restores into a
    non-PP run's per-layer state — and vice versa — via the automatic
    layout conversion (round-2 ADVICE item; engine/checkpoint.py).  Values
    must round-trip exactly; the optimizer step counter and moment trees
    convert with the params."""
    from pytorch_distributed_training_tpu.engine import TrainState
    from pytorch_distributed_training_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import (
        make_pp_mesh,
        pp_stack_params,
        pp_state_shardings,
        replicated_sharding,
    )
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh

    depth = 4
    model = TransformerLM(
        vocab_size=32, max_len=8, embed_dim=16, depth=depth, num_heads=2,
        seq_axis=None,
    )
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    opt = SGD(lr=0.1, momentum=0.9)

    # --- flat checkpoint -> PP state -----------------------------------
    mesh = make_mesh()
    flat_state = TrainState(
        params=params, batch_stats={}, opt_state=opt.init(params)
    )
    flat_state = jax.device_put(flat_state, replicated_sharding(mesh))
    ck1 = Checkpointer(str(tmp_path / "flat"), interval=1)
    ck1.save(5, flat_state)
    ck1.wait()

    pp_mesh = make_pp_mesh(4)
    pp_params = pp_stack_params(params, depth)
    pp_state = TrainState(
        params=jax.tree.map(jnp.zeros_like, pp_params),
        batch_stats={},
        opt_state=opt.init(jax.tree.map(jnp.zeros_like, pp_params)),
    )
    pp_state = jax.device_put(pp_state, pp_state_shardings(pp_state, pp_mesh))
    restored, next_iter = ck1.restore_latest(pp_state)
    ck1.close()
    assert next_iter == 6
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(pp_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # stage shardings of the target were applied
    assert restored.params["blocks"]["attn"]["qkv"]["kernel"].sharding.spec[0] == "stage"

    # --- PP checkpoint -> flat state -----------------------------------
    pp_src = TrainState(
        params=pp_params, batch_stats={}, opt_state=opt.init(pp_params)
    )
    pp_src = jax.device_put(pp_src, pp_state_shardings(pp_src, pp_mesh))
    ck2 = Checkpointer(str(tmp_path / "pp"), interval=1)
    ck2.save(9, pp_src)
    ck2.wait()

    flat_target = TrainState(
        params=jax.tree.map(jnp.zeros_like, params),
        batch_stats={},
        opt_state=opt.init(jax.tree.map(jnp.zeros_like, params)),
    )
    flat_target = jax.device_put(flat_target, replicated_sharding(mesh))
    restored2, next_iter2 = ck2.restore_latest(flat_target)
    ck2.close()
    assert next_iter2 == 10
    for a, b in zip(jax.tree.leaves(restored2.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_nonstructural_error_not_misdiagnosed(tmp_path):
    """A corrupt checkpoint (array data destroyed, structure unchanged)
    must raise the ORIGINAL IO/orbax error — not the layout-mismatch
    RuntimeError, whose pp_stack/unstack advice would send the operator
    debugging pipeline settings instead of the disk.  Structural-vs-IO is
    decided from the checkpoint's stored tree metadata
    (Checkpointer._structure_differs), not error-string keywords."""
    import os
    import shutil

    from pytorch_distributed_training_tpu.engine import TrainState
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_training_tpu.parallel import replicated_sharding

    params = {"w": jnp.ones((4, 4))}
    opt = SGD(lr=0.1)
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, replicated_sharding(make_mesh()))
    ck = Checkpointer(str(tmp_path / "c"), interval=1)
    ck.save(3, state)
    ck.wait()
    # same structure, destroyed payload: gut every array store's contents
    # under the step dir (keep the directory skeleton so metadata-based
    # structure detection still sees a matching tree where possible)
    step_dir = os.path.join(ck.directory, "3")
    removed = 0
    for root, dirs, files in os.walk(step_dir):
        for f in files:
            if f not in ("_METADATA", "metadata", "manifest.ocdbt"):
                os.remove(os.path.join(root, f))
                removed += 1
    assert removed > 0, "corruption setup removed nothing"
    with pytest.raises(Exception) as exc_info:
        ck.restore_latest(state)
    ck.close()
    # it must NOT be the layout-mismatch wrapper
    assert "pp_stack_params" not in str(exc_info.value), (
        "corruption misdiagnosed as a params-layout mismatch:\n"
        f"{exc_info.value}"
    )


def test_corrupt_newest_checkpoint_falls_back_to_previous_valid(tmp_path, caplog):
    """Fault-tolerance satellite: a truncated/corrupt NEWEST checkpoint is
    skipped with a warning and restore_latest falls back to the newest
    EARLIER valid step — a partial write during eviction must not brick the
    relaunch.  (A single corrupt step with nothing to fall back to still
    raises the raw error: test_restore_nonstructural_error_not_misdiagnosed.)"""
    import logging
    import os

    from pytorch_distributed_training_tpu.engine import TrainState, fault
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import replicated_sharding
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_training_tpu.utils.retry import Retry

    opt = SGD(lr=0.1)

    def make_state(fill):
        params = {"w": jnp.full((4, 4), float(fill))}
        state = TrainState(
            params=params, batch_stats={}, opt_state=opt.init(params)
        )
        return jax.device_put(state, replicated_sharding(make_mesh()))

    # attempts=1: the corrupt step must fail over to the previous step, not
    # burn retry backoff on a permanently damaged directory
    ck = Checkpointer(str(tmp_path / "c"), interval=1, retry=Retry(attempts=1))
    ck.save(1, make_state(1.0))
    ck.save(3, make_state(3.0))
    ck.wait()
    step_dir = os.path.join(ck.directory, "3")
    removed = 0
    for root, dirs, files in os.walk(step_dir):
        for f in files:
            if f not in ("_METADATA", "metadata", "manifest.ocdbt"):
                os.remove(os.path.join(root, f))
                removed += 1
    assert removed > 0, "corruption setup removed nothing"

    fault.reset_counters()
    logger = logging.getLogger("ckpt-fallback-test")
    with caplog.at_level(logging.WARNING, logger=logger.name):
        restored, next_iter = ck.restore_latest(make_state(0.0), logger)
    ck.close()
    assert next_iter == 2  # step 1 restored, not the corrupt step 3
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.full((4, 4), 1.0)
    )
    assert fault.counters().get("ckpt_fallbacks") == 1
    assert any("falling back" in r.getMessage() for r in caplog.records)


def test_orbax_metadata_contract_version_guard(monkeypatch):
    """The layout-vs-corruption discriminator leans on orbax's (undocumented)
    item_metadata tree-structure convention.  The installed orbax must be
    inside the verified range, and outside it the discriminator must decline
    to classify (return False -> raw restore errors re-raise) rather than
    risk misreading a changed metadata layout as a checkpoint-layout
    mismatch (round-4 VERDICT #8 / round-3 ADVICE #3)."""
    import orbax.checkpoint as ocp

    from pytorch_distributed_training_tpu.engine import checkpoint as ckpt_mod

    # (a) the baked-in orbax is inside the verified range
    assert ckpt_mod._orbax_metadata_contract_ok(), (
        f"installed orbax {ocp.__version__} is outside "
        f"{ckpt_mod._ORBAX_METADATA_CONTRACT_RANGE}; re-verify the "
        "item_metadata contract (wrong-layout restore tests above) and "
        "extend the range"
    )

    # (b) outside the range, _structure_differs declines without touching
    # the manager (guard short-circuits before any metadata read)
    monkeypatch.setattr(ocp, "__version__", "99.0.0")
    assert not ckpt_mod._orbax_metadata_contract_ok()
    differs = Checkpointer._structure_differs(
        object.__new__(Checkpointer), 0, {"w": jnp.ones(2)}
    )
    assert differs is False


# ----------------------------------------------------------------------
# Cross-topology restore (round-3 VERDICT #6): a checkpoint written under
# one parallelism layout must restore into another whenever the LOGICAL
# state tree matches — orbax reshards to the target's shardings.  Layouts
# that genuinely differ (stacked PP params) stay descriptive errors
# (covered above).
# ----------------------------------------------------------------------
def _lm_cfg(tmp_path, train_iters=2, **train_extra):
    return {
        "dataset": {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {
                "name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4, "momentum": 0.9,
            },
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": train_iters,
            "print_interval": 10,
            "val_interval": 100,
            "batch_size": 16,
            "num_workers": 1,
            "sync_bn": False,
            "checkpoint": {"dir": str(tmp_path / "ckpt"), "interval": 2},
            **train_extra,
        },
        "validation": {"batch_size": 16, "num_workers": 1},
        "model": {"name": "TransformerLM", "embed_dim": 32, "depth": 2,
                  "num_heads": 4},
    }


class _SetupOnlyRunner(Runner):
    """Runs worker setup (incl. restore); skips the training loop."""

    def _train_loop(self, iter_generator, train_cfg):
        self.captured_iter = self.iter


def _setup_only(cfg):
    runner = _SetupOnlyRunner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9902",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None,
    )
    runner()
    return runner


from tree_utils import flat_tree as _flat  # single source of the key format


@pytest.mark.parametrize(
    "target_extra",
    [{"tensor_parallelism": 2}, {"zero": 1}, {"zero": 2}, {"zero": 3}],
    ids=["tp2", "zero1", "zero2", "zero3"],
)
def test_dp_checkpoint_restores_into_resharded_run(tmp_path, target_extra):
    """A plain-DP LM checkpoint restores into TP=2 / ZeRO-1 / ZeRO-2 runs:
    identical values, target-topology shardings (orbax resharding)."""
    writer = _run(_lm_cfg(tmp_path, train_iters=2))
    want_params = _flat(writer.state.params)
    want_mu = _flat(writer.state.opt_state.momentum)

    reader = _setup_only(_lm_cfg(tmp_path, train_iters=2, **target_extra))
    assert reader.captured_iter == 2  # resumed past the saved step
    got_params = _flat(reader.state.params)
    got_mu = _flat(reader.state.opt_state.momentum)
    assert set(got_params) == set(want_params)
    for name in want_params:
        np.testing.assert_array_equal(got_params[name], want_params[name], err_msg=name)
    for name in want_mu:
        np.testing.assert_array_equal(got_mu[name], want_mu[name], err_msg=name)

    # the restored state is in the TARGET topology's layout, not the writer's
    from conftest import uses_mesh_axis

    flat_live = _flat(reader.state.params, materialize=False)
    if "tensor_parallelism" in target_extra:
        assert uses_mesh_axis(
            flat_live["block0/attn/qkv/kernel"].sharding, "model"
        )
    else:
        flat_mu_live = _flat(reader.state.opt_state.momentum, materialize=False)
        assert uses_mesh_axis(
            flat_mu_live["block0/attn/qkv/kernel"].sharding, "data"
        )
    # and the compiled step accepts it (one extra iteration runs cleanly)
    cont = _run(_lm_cfg(tmp_path, train_iters=3, **target_extra))
    assert int(cont.state.step) == 3


# ----------------------------------------------------------------------
# Async overlapped checkpointing (ISSUE 5): the save step blocks only for
# the host snapshot; the write happens on a background thread with errors
# deferred to the next synchronization point, the sidecar strictly after
# the commit, and a crash mid-write indistinguishable from the existing
# truncated-checkpoint fallback case.
# ----------------------------------------------------------------------
def _tiny_state(fill):
    from pytorch_distributed_training_tpu.engine import TrainState
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import replicated_sharding
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh

    opt = SGD(lr=0.1, momentum=0.9)
    params = {"w": jnp.full((8, 4), float(fill)), "b": jnp.full((4,), float(fill))}
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    return jax.device_put(state, replicated_sharding(make_mesh()))


def test_async_save_commits_and_roundtrips(tmp_path):
    """Async saves commit durably (values round-trip exactly), write the
    sidecar only after the commit, and prune sidecars exactly on the
    garbage-collection events that evict their steps."""
    import os

    ck = Checkpointer(str(tmp_path / "c"), interval=1, max_to_keep=2,
                      async_save=True, max_inflight=1)
    assert ck.async_save and ck.max_inflight == 1
    for it in range(4):
        ck.save(it, _tiny_state(it), extras={"epoch": it})
    ck.wait()  # commit barrier: every enqueued write is durable past here
    assert ck.all_steps() == [2, 3]  # max_to_keep=2 evicted steps 0 and 1
    # evicted steps lost their sidecars on the GC event; kept steps didn't
    sidecars = sorted(
        f for f in os.listdir(str(tmp_path / "c")) if f.startswith("pipeline_")
    )
    assert sidecars == ["pipeline_2.json", "pipeline_3.json"]
    assert ck.read_extras(3) == {"epoch": 3}

    restored, next_iter = ck.restore_latest(_tiny_state(0.0))
    ck.close()
    assert next_iter == 4
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.full((8, 4), 3.0)
    )


def test_async_config_surface(tmp_path):
    """training.checkpoint.async / max_inflight parse additively; a
    nonsensical inflight bound is rejected at construction."""
    ck = Checkpointer.from_config({
        "checkpoint": {"dir": str(tmp_path / "a"), "async": True,
                       "max_inflight": 2},
    })
    assert ck.async_save and ck.max_inflight == 2
    ck.close()
    ck2 = Checkpointer.from_config({"checkpoint": {"dir": str(tmp_path / "b")}})
    assert not ck2.async_save  # default off: sync semantics unchanged
    ck2.close()
    with pytest.raises(ValueError, match="max_inflight"):
        Checkpointer(str(tmp_path / "x"), max_inflight=0)


def test_async_write_failure_surfaces_at_next_sync_point(tmp_path):
    """A background write that exhausts its retry budget must not vanish:
    the NEXT save (a synchronization point) raises AsyncCheckpointError
    chaining the storage error, and the failed step is never visible to
    restore."""
    from pytorch_distributed_training_tpu.engine import fault
    from pytorch_distributed_training_tpu.engine.checkpoint import (
        AsyncCheckpointError,
    )
    from pytorch_distributed_training_tpu.engine.fault import FaultInjectionError
    from pytorch_distributed_training_tpu.utils.retry import Retry

    fault.reset_counters()
    ck = Checkpointer(str(tmp_path / "c"), interval=1, async_save=True,
                      retry=Retry(attempts=1))
    try:
        ck.save(0, _tiny_state(0.0))
        ck.wait()  # step 0 durably committed before the fault window opens
        fault.install("ckpt_async_fail@0:99")
        ck.save(1, _tiny_state(1.0))  # background write fails, no budget left
        with pytest.raises(AsyncCheckpointError, match="step 1") as exc_info:
            ck.save(2, _tiny_state(2.0))
        assert isinstance(exc_info.value.__cause__, FaultInjectionError)
        assert fault.counters().get("injected_ckpt_async_write_failures") == 1
        # recovery flavor: drain without raising drops the failure (logged)
        ck.drain(raise_errors=False)
        assert ck.all_steps() == [0]  # the failed write never committed
        restored, next_iter = ck.restore_latest(_tiny_state(9.0))
        assert next_iter == 1  # previous committed step restores
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((8, 4), 0.0)
        )
    finally:
        ck.close()
        fault.install(None)
        fault.reset_counters()


def test_crash_during_async_write_falls_back_like_truncated_step(tmp_path):
    """Kill-during-async-write (extends the corrupt-fallback battery): the
    interrupted write leaves only an UNCOMMITTED tmp step dir — orbax's
    atomic-rename commit never ran — so restore_latest must treat it like
    the truncated-checkpoint case and hand back the previous committed
    step, without even burning a fallback."""
    import os

    from pytorch_distributed_training_tpu.engine import fault
    from pytorch_distributed_training_tpu.utils.retry import Retry

    fault.reset_counters()
    ck = Checkpointer(str(tmp_path / "c"), interval=1, async_save=True,
                      retry=Retry(attempts=1))
    try:
        ck.save(1, _tiny_state(1.0))
        ck.wait()
        fault.install("ckpt_async_fail@0:99")
        ck.save(3, _tiny_state(3.0))  # dies on the writer thread
        ck.drain(raise_errors=False)
        # the crash artifact a mid-write kill leaves on disk: a partial,
        # uncommitted tmp directory for the step
        tmp_dir = os.path.join(ck.directory, "3.orbax-checkpoint-tmp-123456")
        os.makedirs(tmp_dir)
        with open(os.path.join(tmp_dir, "partial"), "w") as fp:
            fp.write("truncated")

        assert ck.all_steps() == [1]  # the tmp dir is invisible
        restored, next_iter = ck.restore_latest(_tiny_state(0.0))
        assert next_iter == 2
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((8, 4), 1.0)
        )
        # no fallback was needed: the uncommitted step was never a candidate
        assert "ckpt_fallbacks" not in fault.counters()
    finally:
        ck.close()
        fault.install(None)
        fault.reset_counters()


@pytest.mark.slow
def test_sidecar_missing_for_committed_step_tolerated(tmp_path, one_device_graft):
    """Satellite regression (sidecar/commit ordering): a checkpoint whose
    sidecar is gone — the old ordering could crash between manager.save and
    the sidecar write; GC pruning can also race a crash — must still
    resume, deriving the pipeline position from the step counter."""
    import os

    _run(_cfg(tmp_path, train_iters=2))  # interval=2 -> save at step 1
    sidecar = os.path.join(str(tmp_path / "ckpt"), "pipeline_1.json")
    assert os.path.exists(sidecar)
    os.remove(sidecar)  # the crash-at-the-boundary artifact

    ck = Checkpointer(str(tmp_path / "ckpt"))
    assert ck.read_extras(1) is None  # absence-tolerant, no raise
    ck.close()

    resumed = _run(_cfg(tmp_path, train_iters=4))
    assert resumed.iter == 4  # resumed from step 1 without the sidecar


@pytest.mark.slow
def test_resume_bit_exact_async_vs_straight_run(tmp_path, one_device_graft):
    """The async-save pipeline end to end through the Runner: 4 iters
    straight == 2 iters + async checkpoint + resume 2 more, bit-exact —
    the snapshot/overlapped write must save exactly the state the sync
    path would have."""
    straight = _run(_cfg(tmp_path / "a", ckpt=False, train_iters=4))

    cfg_b = _cfg(tmp_path / "b", train_iters=2)
    cfg_b["training"]["checkpoint"]["async"] = True
    _run(cfg_b)
    cfg_b2 = _cfg(tmp_path / "b", train_iters=4)
    cfg_b2["training"]["checkpoint"]["async"] = True
    resumed = _run(cfg_b2)

    a = jax.tree.map(np.asarray, straight.state.params)
    b = jax.tree.map(np.asarray, resumed.state.params)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(x, y)


@pytest.mark.slow
def test_bench_ckpt_cli():
    """End-to-end ``bench.py ckpt`` at a tiny config: one JSON line with
    the sync/async stall A/B, bytes written, overlap efficiency, and the
    kill-during-async-write probe restoring the previous committed step."""
    import json as _json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PDT_JAX_COMPAT="1",  # inert on grafted JAX; single device = exact
        PYTHONPATH=root + os.pathsep + env.get("PYTHONPATH", ""),
        BENCH_CKPT_ITERS="8", BENCH_CKPT_INTERVAL="4",
        BENCH_CKPT_VOCAB="256", BENCH_CKPT_SEQ="32", BENCH_CKPT_EMBED="32",
        BENCH_CKPT_DEPTH="2", BENCH_CKPT_HEADS="4", BENCH_CKPT_BATCH="2",
        BENCH_COMPILE_CACHE="0",
    )
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "ckpt"],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = _json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["unit"] == "ms"
    assert out["nonsave_step_ms"] > 0
    assert out["sync_save_step_ms"] > 0 and out["async_save_step_ms"] > 0
    assert out["bytes_written"] > 0
    # the chaos probe: the killed background write never committed, and
    # restore handed back the previous durable step
    assert out["chaos_uncommitted_step_dropped"] is True
    assert out["chaos_resume_iter"] == 1
    assert out.get("chaos_injected_ckpt_async_write_failures", 0) >= 1
    # at this toy size timing is noise; the acceptance-bar stall numbers
    # are checked on the real bench config (PERF.md), not here — but the
    # fields must exist for the driver to read
    assert "overlap_efficiency" in out and "sync_stall_ms" in out


@pytest.mark.slow
def test_restore_at_different_device_count(tmp_path):
    """batch_division: world — a checkpoint written on the 8-device mesh
    restores in a 4-device process (orbax resharding across world sizes),
    bit-identical params."""
    import subprocess
    import sys
    import json as _json
    import os

    cfg = _lm_cfg(tmp_path, train_iters=2, batch_division="world")
    writer = _run(cfg)
    want = _flat(writer.state.params)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(_json.dumps(cfg))
    out_path = tmp_path / "restored.npz"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(
        RW_DEVICES="4", RW_CFG=str(cfg_path), RW_OUT=str(out_path),
        PYTHONPATH=root + os.pathsep + env.get("PYTHONPATH", ""),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tests", "restore_worker.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(str(out_path) + ".json") as fp:
        meta = _json.load(fp)
    assert meta["device_count"] == 4
    assert meta["restored_iter"] == 2
    got = dict(np.load(str(out_path)))
    assert set(got) == set(want)
    for name in want:
        np.testing.assert_array_equal(got[name], want[name], err_msg=name)
