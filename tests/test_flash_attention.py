"""Pallas flash attention vs the naive reference (interpreter mode on CPU).

Same evidence pattern as the fused-CE kernel tests: the kernel must match
the XLA einsum attention (forward AND backward, causal and full) on the
CPU test mesh via the Pallas interpreter — including inside ``shard_map``,
where the vma typing exercised by the production call site
(engine/sp_steps runs the model under shard_map) applies.  Real-TPU
numbers are recorded in PERF.md.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_tpu.ops.attention import dot_product_attention
from pytorch_distributed_training_tpu.ops.flash_attention import flash_attention

B, S, H, D = 2, 256, 4, 32


def _qkv(seed=0, s=S):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, s, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_naive(causal):
    """dq/dk/dv through the custom VJP == autodiff of the naive path (the
    sin() wrapper makes the cotangent non-constant so all three grads are
    nontrivial)."""
    q, k, v = _qkv(seed=1)

    def f(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(
        f(lambda q, k, v: dot_product_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_fa = jax.grad(
        f(lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("causal", [False, True])
def test_multi_k_block_online_softmax(causal):
    """S=1536 = 3 K blocks of 512 x 6 Q tiles of 256 (the production
    asymmetric tile pair): the online-softmax rescaling across K blocks
    (m/l carry), the causal nj loop bound, and the dkv i0 start all run
    multiple iterations — forward AND all three grads vs the naive
    reference (the r2 review caught the 512 tile silently single-blocking
    the old S=384 version of this test)."""
    q, k, v = _qkv(seed=2, s=1536)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=causal))),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_fa = jax.grad(
        lambda q, k, v: jnp.sum(
            jnp.sin(flash_attention(q, k, v, causal=causal, interpret=True))
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


def test_halved_tile_fallback():
    """S=384: bq falls back to 128 (256 does not divide) while bk becomes a
    whole-array tile — the mixed fallback geometry must stay exact."""
    q, k, v = _qkv(seed=2, s=384)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bf16_inputs():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(seed=3))
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_inside_shard_map_with_grad():
    """The production context (engine/sp_steps): kernel under shard_map
    with batch sharded over the mesh — forward and grads must equal the
    single-device naive computation (vma typing + psum-free locality)."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    q, k, v = _qkv(seed=4)

    def local(q, k, v):
        def loss(q):
            return jnp.sum(
                jnp.sin(flash_attention(q, k, v, causal=True, interpret=True))
            )

        l, g = jax.value_and_grad(loss)(q)
        return jax.lax.psum(l, "data"), g

    # check_vma=False: the Pallas INTERPRETER's state discharge does not
    # propagate varying-axes through the kernels' in-kernel pl.ds reads
    # (mixed-vma dynamic_slice errors); real-TPU Mosaic lowering never
    # discharges, so the production shard_map paths (engine/sp_steps) are
    # unaffected — this flag is test-harness-only.
    sharded = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P(), P("data")),
        check_vma=False,
    )
    loss_sh, grad_sh = sharded(q, k, v)

    def ref_loss(q):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    loss_ref, grad_ref = jax.value_and_grad(ref_loss)(q)
    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad_sh), np.asarray(grad_ref), atol=5e-5
    )


def test_block_picker_edge_lengths():
    """Ragged lengths run as one whole-array tile — both below the
    preferred tile (s=200) and above it with no 8-aligned power-of-two
    factor (s=514 = 2x257): every length is legal, only the auto-dispatch
    gates (s % 128) decide what runs in production."""
    for s in (200, 514):
        q, k, v = _qkv(seed=5, s=s)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, err_msg=f"s={s}"
        )


def test_dispatch_gate_cpu_and_override():
    """On the CPU backend the auto path must stay XLA (impl=None), and the
    explicit impl='xla' override must always work."""
    from pytorch_distributed_training_tpu.ops.attention import _use_flash

    q, k, v = _qkv(seed=6)
    assert not _use_flash(q)  # cpu backend
    out = dot_product_attention(q, k, v, causal=True, impl="xla")
    assert out.shape == q.shape
    with pytest.raises(ValueError, match="impl"):
        dot_product_attention(q, k, v, impl="pallas")
