"""Pallas flash attention vs the naive reference (interpreter mode on CPU).

Same evidence pattern as the fused-CE kernel tests: the kernel must match
the XLA einsum attention (forward AND backward, causal and full) on the
CPU test mesh via the Pallas interpreter — including inside ``shard_map``,
where the vma typing exercised by the production call site
(engine/sp_steps runs the model under shard_map) applies.  Real-TPU
numbers are recorded in PERF.md.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_tpu.ops.attention import dot_product_attention
from pytorch_distributed_training_tpu.ops.flash_attention import flash_attention

B, S, H, D = 2, 256, 4, 32


def _qkv(seed=0, s=S):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, s, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.quick
def test_forward_matches_naive(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_naive(causal):
    """dq/dk/dv through the custom VJP == autodiff of the naive path (the
    sin() wrapper makes the cotangent non-constant so all three grads are
    nontrivial)."""
    q, k, v = _qkv(seed=1)

    def f(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(
        f(lambda q, k, v: dot_product_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_fa = jax.grad(
        f(lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("causal", [False, True])
def test_multi_k_block_online_softmax(causal):
    """S=1536 = 3 K blocks of 512 x 6 Q tiles of 256 (the production
    asymmetric tile pair): the online-softmax rescaling across K blocks
    (m/l carry), the causal nj loop bound, and the dkv i0 start all run
    multiple iterations — forward AND all three grads vs the naive
    reference (the r2 review caught the 512 tile silently single-blocking
    the old S=384 version of this test)."""
    q, k, v = _qkv(seed=2, s=1536)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=causal))),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_fa = jax.grad(
        lambda q, k, v: jnp.sum(
            jnp.sin(flash_attention(q, k, v, causal=causal, interpret=True))
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


def test_halved_tile_fallback():
    """S=384: bq falls back to 128 (256 does not divide) while bk becomes a
    whole-array tile — the mixed fallback geometry must stay exact."""
    q, k, v = _qkv(seed=2, s=384)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bf16_inputs():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(seed=3))
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_inside_shard_map_with_grad():
    """The production context (engine/sp_steps): kernel under shard_map
    with batch sharded over the mesh — forward and grads must equal the
    single-device naive computation (vma typing + psum-free locality)."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    q, k, v = _qkv(seed=4)

    def local(q, k, v):
        def loss(q):
            return jnp.sum(
                jnp.sin(flash_attention(q, k, v, causal=True, interpret=True))
            )

        l, g = jax.value_and_grad(loss)(q)
        return jax.lax.psum(l, "data"), g

    # check_vma=False: the Pallas INTERPRETER's state discharge does not
    # propagate varying-axes through the kernels' in-kernel pl.ds reads
    # (mixed-vma dynamic_slice errors); real-TPU Mosaic lowering never
    # discharges, so the production shard_map paths (engine/sp_steps) are
    # unaffected — this flag is test-harness-only.
    sharded = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P(), P("data")),
        check_vma=False,
    )
    loss_sh, grad_sh = sharded(q, k, v)

    def ref_loss(q):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    loss_ref, grad_ref = jax.value_and_grad(ref_loss)(q)
    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad_sh), np.asarray(grad_ref), atol=5e-5
    )


def test_block_picker_edge_lengths():
    """Ragged lengths run as one whole-array tile — both below the
    preferred tile (s=200) and above it with no 8-aligned power-of-two
    factor (s=514 = 2x257): every length is legal, only the auto-dispatch
    gates (s % 128) decide what runs in production."""
    for s in (200, 514):
        q, k, v = _qkv(seed=5, s=s)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, err_msg=f"s={s}"
        )


def test_dispatch_gate_cpu_and_override():
    """On the CPU backend the auto path must stay XLA (impl=None), and the
    explicit impl='xla' override must always work."""
    from pytorch_distributed_training_tpu.ops.attention import _use_flash

    q, k, v = _qkv(seed=6)
    assert not _use_flash(q)  # cpu backend
    out = dot_product_attention(q, k, v, causal=True, impl="xla")
    assert out.shape == q.shape
    with pytest.raises(ValueError, match="impl"):
        dot_product_attention(q, k, v, impl="pallas")


# ----------------------------------------------------------------------
# Streamed kernels (round-3: K/V tiles ride the innermost grid dim, VMEM
# O(block*D) — lifts the resident kernels' S<=8k@D=128 ceiling).  Forced
# via PDT_FLASH_FORCE_STREAM so CPU-sized shapes exercise the streaming
# code path; real-TPU S=16384, D=128 fwd+bwd evidence is in PERF.md.
# ----------------------------------------------------------------------
@pytest.fixture
def force_stream(monkeypatch):
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    monkeypatch.setenv("PDT_FLASH_FORCE_STREAM", "1")
    fa._make.cache_clear()
    yield
    fa._make.cache_clear()


@pytest.mark.parametrize("causal", [False, True])
def test_streamed_forward_matches_naive(causal, force_stream):
    # s=1024 with (256, 512) tiles: 4 Q tiles x 2 K tiles, so the streaming
    # carry crosses a real K-tile boundary (online-softmax state in scratch)
    q, k, v = _qkv(seed=7, s=1024)
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_streamed_backward_matches_naive(causal, force_stream):
    q, k, v = _qkv(seed=8, s=1024)

    def f(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(
        f(lambda q, k, v: dot_product_attention(q, k, v, causal=causal, impl="xla")),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_fa = jax.grad(
        f(lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


def test_streamed_matches_resident_bitwise(force_stream):
    """Same blocks, same f32 accumulate order => the streamed kernels are
    not just close to the resident ones, they are IDENTICAL (the grid-dim
    loop visits K tiles in the same order as the in-kernel fori_loop)."""
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    q, k, v = _qkv(seed=9, s=512)
    o_stream = np.asarray(flash_attention(q, k, v, causal=True, interpret=True))
    fa._make.cache_clear()
    import os

    del os.environ["PDT_FLASH_FORCE_STREAM"]
    o_res = np.asarray(flash_attention(q, k, v, causal=True, interpret=True))
    np.testing.assert_array_equal(o_stream, o_res)


def test_streamed_lse_grad(force_stream):
    """The lse output and its cotangent path (ring-attention's combine
    consumes lse) stay exact through the streamed backward kernels."""
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention_lse,
    )

    q, k, v = _qkv(seed=10, s=1024)

    def f_flash(q, k, v):
        o, lse = flash_attention_lse(q, k, v, causal=True, interpret=True)
        return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(lse))

    def f_ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B,H,S]
        p = jnp.exp(s - lse[..., None])
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(jnp.transpose(lse, (0, 2, 1))))

    g_fa = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ref, g_fa, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, err_msg=f"d{name}"
        )


# ----------------------------------------------------------------------
# Fused backward (round-5: one pass produces dq/dk/dv, dK/dV accumulated
# in revisited VMEM-resident f32 output blocks — the split two-pass path
# remains for shapes whose fused footprint exceeds VMEM and as the
# PDT_FLASH_NO_FUSED_BWD escape hatch).
# ----------------------------------------------------------------------
@pytest.fixture
def split_bwd(monkeypatch):
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    monkeypatch.setenv("PDT_FLASH_NO_FUSED_BWD", "1")
    fa._make.cache_clear()
    yield
    fa._make.cache_clear()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_bwd_matches_split_bitwise(causal, dtype, split_bwd, monkeypatch):
    """Fused and split backwards accumulate the same per-tile f32 values in
    the same ascending order with one end-rounding each => bitwise-equal
    grads, in both dot-precision modes (s=1536 runs multiple tile pairs
    incl. the causal loop bounds on both sides).  The split path is pinned
    to the fused path's tile pair: tile geometry determines f32 summation
    ORDER, so bitwise equality is only defined at matching tiles (the
    production defaults differ — fused halves the Q tile for scoped VMEM;
    cross-tile agreement is covered by the naive-reference tolerances)."""
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_BLOCK_Q", fa._BLOCK_Q_FUSED)
    monkeypatch.setattr(fa, "_BLOCK_K", fa._BLOCK_K_FUSED)
    q, k, v = (x.astype(dtype) for x in _qkv(seed=11, s=1536))

    def grads(q, k, v):
        return jax.grad(
            lambda q, k, v: jnp.sum(
                jnp.sin(
                    flash_attention(q, k, v, causal=causal, interpret=True)
                    .astype(jnp.float32)
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)

    g_split = grads(q, k, v)
    fa._make.cache_clear()
    import os

    del os.environ["PDT_FLASH_NO_FUSED_BWD"]
    # guard against vacuous split==split: the second run must actually
    # take the fused kernel
    calls = []
    real_kernel = fa._dqkv_kernel

    def counting_kernel(*args, **kwargs):
        calls.append(1)
        return real_kernel(*args, **kwargs)

    monkeypatch.setattr(fa, "_dqkv_kernel", counting_kernel)
    g_fused = grads(q, k, v)
    assert calls, "fused path was not taken"
    for a, b, name in zip(g_split, g_fused, "qkv"):
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            err_msg=f"d{name}",
        )


def test_fused_bwd_gate():
    """The fused path must bow out for shapes whose K/V + f32 dK/dV blocks
    exceed the VMEM budget (they fall back to the split resident or
    streamed kernels)."""
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        _fused_bwd_ok,
    )

    ok = lambda s, d, i: _fused_bwd_ok(s, d, i, bf16_dots=True, interpret=False)  # noqa: E731
    assert ok(2048, 64, 2)  # the LM bench shape, bf16
    assert ok(8192, 64, 2)
    assert not ok(16384, 64, 2)  # resident edge: split path
    assert not ok(8192, 128, 4)
    # on real TPU, f32 dots overflow the fused kernel's scoped VMEM
    assert not _fused_bwd_ok(2048, 64, 4, bf16_dots=False, interpret=False)
    assert _fused_bwd_ok(2048, 64, 4, bf16_dots=False, interpret=True)


def test_bf16_dots_grad_close_to_f32_dots():
    """The bf16-MXU-rate dot path must track the f32-dot path on bf16
    inputs (products are exact; p/ds round to bf16 before their dots) —
    and PDT_FLASH_F32_DOTS must actually flip the path (observable via
    a numeric difference in p@v rounding)."""
    import os

    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(seed=12, s=512))

    def run():
        fa._make.cache_clear()
        return jax.value_and_grad(
            lambda q: jnp.sum(
                flash_attention(q, k, v, causal=True, interpret=True).astype(
                    jnp.float32
                )
            )
        )(q)

    o_bf, g_bf = run()
    os.environ["PDT_FLASH_F32_DOTS"] = "1"
    try:
        o_f32, g_f32 = run()
    finally:
        del os.environ["PDT_FLASH_F32_DOTS"]
        fa._make.cache_clear()
    np.testing.assert_allclose(float(o_bf), float(o_f32), rtol=2e-2)
    np.testing.assert_allclose(
        np.asarray(g_bf, np.float32), np.asarray(g_f32, np.float32), atol=2e-1
    )
    # the flag must actually flip the path: p rounds to bf16 before the
    # p@v dot only on the bf16-dots side, so bit-identical grads mean the
    # escape hatch silently died (the cb874f2 bug class)
    assert not np.array_equal(
        np.asarray(g_bf, np.float32), np.asarray(g_f32, np.float32)
    )


def test_gate_no_longer_caps_sequence():
    """flash_shapes_ok must accept sequences past the old resident-VMEM
    ceiling (S=8192@D=128) — those dispatch to the streamed kernels now."""
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_shapes_ok,
    )

    assert flash_shapes_ok(16384, 128)
    assert flash_shapes_ok(65536, 128)
    assert not flash_shapes_ok(100, 64)  # still requires s % 128 == 0
