"""Label smoothing (torch parity) + params EMA.

``training.label_smoothing`` must match ``torch.nn.CrossEntropyLoss``'s
convention exactly; ``training.ema`` maintains an exponential moving
average of the params inside the compiled step and validation runs on the
averaged weights.
"""
import jax
import jax.numpy as jnp
import numpy as np
import torch

from pytorch_distributed_training_tpu.engine import (
    Runner,
    build_train_step,
    init_train_state,
)
from pytorch_distributed_training_tpu.models import get_model
from pytorch_distributed_training_tpu.ops import cross_entropy_loss
from pytorch_distributed_training_tpu.ops.losses import cross_entropy_loss_xla
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_training_tpu.schedulers import multi_step_lr


def test_label_smoothing_matches_torch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, (16,)).astype(np.int64)
    for s in (0.0, 0.1, 0.3):
        want = torch.nn.CrossEntropyLoss(label_smoothing=s)(
            torch.tensor(logits), torch.tensor(labels)
        ).item()
        got = float(cross_entropy_loss_xla(jnp.asarray(logits), jnp.asarray(labels), s))
        np.testing.assert_allclose(got, want, rtol=1e-6)
        # the dispatcher path (hard fused CE + correction on TPU, plain XLA
        # here) must agree with the closed form either way
        got2 = float(cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels), s))
        np.testing.assert_allclose(got2, want, rtol=1e-6)


def test_fused_correction_algebra():
    """smooth == hard + s * mean(true_logit - mean_logit) — the identity the
    fused-kernel path relies on."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(8, 12)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 12, (8,)).astype(np.int32))
    s = 0.2
    hard = cross_entropy_loss_xla(logits, labels, 0.0)
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    composed = hard + s * jnp.mean(true_logit - jnp.mean(logits, axis=-1))
    direct = cross_entropy_loss_xla(logits, labels, s)
    np.testing.assert_allclose(float(composed), float(direct), rtol=1e-6)


def test_ema_follows_recursion():
    mesh = make_mesh()
    model = get_model("ViT-Ti16", num_classes=8)
    opt = SGD(lr=0.05, momentum=0.9)
    lr_fn = multi_step_lr(0.05, [1000], 0.1)
    state = init_train_state(
        model, opt, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
    )
    decay = 0.9
    state = state.replace(ema=state.params)
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_train_step(
        model, opt, lr_fn, mesh, sync_bn=False, donate=False, ema_decay=decay
    )
    rng = np.random.default_rng(2)
    img = jax.device_put(
        rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
        batch_sharding(mesh, 4),
    )
    lab = jax.device_put(rng.integers(0, 8, (16,)).astype(np.int32), batch_sharding(mesh, 1))

    manual = jax.tree.map(np.asarray, state.ema)
    for _ in range(3):
        state, _ = step(state, img, lab)
        manual = jax.tree.map(
            lambda e, p: decay * e + (1 - decay) * np.asarray(p),
            manual,
            state.params,
        )
    for a, b in zip(jax.tree.leaves(state.ema), jax.tree.leaves(manual)):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6, atol=1e-7)


def test_runner_ema_and_smoothing_end_to_end(tmp_path):
    scalars = []

    class _TB:
        def add_scalar(self, tag, value, step):
            scalars.append((tag, float(value), step))

    cfg = {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 8,
            "image_size": 32,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {"name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4, "momentum": 0.9},
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": 4,
            "print_interval": 2,
            "val_interval": 3,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": True,
            "label_smoothing": 0.1,
            "ema": {"decay": 0.99},
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ResNet18"},
    }
    runner = Runner(
        num_nodes=1, rank=0, seed=1029, dist_url="tcp://127.0.0.1:9971",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=_TB,
    )
    runner()
    assert runner.iter == 4
    # the EMA tree exists, is populated, and lags the raw params
    ema_leaves = jax.tree.leaves(runner.state.ema)
    assert ema_leaves
    diffs = [
        float(np.max(np.abs(np.asarray(e) - np.asarray(p))))
        for e, p in zip(ema_leaves, jax.tree.leaves(runner.state.params))
    ]
    assert max(diffs) > 0
    assert any(t == "eval/Acc@1" for t, _, _ in scalars)
