"""Device-prefetch iterator: ordering, depth, exhaustion."""
import pytest

from pytorch_distributed_training_tpu.data import device_prefetch


def test_order_preserved_and_all_yielded():
    src = iter([(i,) for i in range(7)])
    calls = []

    def put(x):
        calls.append(x)
        return ("dev", x)

    out = list(device_prefetch(src, put, depth=2))
    assert out == [("dev", i) for i in range(7)]
    assert calls == list(range(7))


def test_put_runs_ahead_of_consumption():
    src = iter([(i,) for i in range(5)])
    staged = []
    gen = device_prefetch(src, lambda x: staged.append(x) or x, depth=3)
    first = next(gen)
    assert first == 0
    # with depth=3, transfers for 0,1,2 were dispatched before the first
    # yield, and yielding one triggers dispatch of the next
    assert staged == [0, 1, 2, 3]


def test_short_stream_and_empty():
    assert list(device_prefetch(iter([(1,), (2,)]), lambda x: x, depth=4)) == [1, 2]
    assert list(device_prefetch(iter([]), lambda x: x, depth=2)) == []


def test_bad_depth():
    with pytest.raises(ValueError):
        list(device_prefetch(iter([]), lambda x: x, depth=0))
