"""Native C++ host-pipeline kernels: build, correctness vs numpy, loader wiring."""
import numpy as np
import pytest

from pytorch_distributed_training_tpu.native import (
    ensure_built,
    native_available,
    normalize_batch,
)


def test_builds_and_loads():
    assert ensure_built(), "native library should build with the baked toolchain"
    assert native_available()


def test_normalize_matches_numpy():
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 256, size=(16, 24, 24, 3), dtype=np.uint8)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)

    ref = ((batch.astype(np.float32) / 255.0) - mean) / std
    out = normalize_batch(batch, mean, std)
    assert out.dtype == np.float32
    assert out.shape == batch.shape
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_normalize_single_thread_matches_multi():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, size=(7, 10, 10, 3), dtype=np.uint8)
    mean = np.array([0.5, 0.5, 0.5], np.float32)
    std = np.array([0.25, 0.25, 0.25], np.float32)
    a = normalize_batch(batch, mean, std, n_threads=1)
    b = normalize_batch(batch, mean, std, n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_rejects_bad_input():
    with pytest.raises(ValueError):
        normalize_batch(np.zeros((2, 4, 4, 3), np.float32), np.ones(3), np.ones(3))
    with pytest.raises(ValueError):
        normalize_batch(np.zeros((4, 4, 3), np.uint8), np.ones(3), np.ones(3))


def test_image_folder_uses_native_path(tmp_path):
    """End-to-end: ImageFolder -> loader -> normalized float batch."""
    from PIL import Image

    from pytorch_distributed_training_tpu.data import (
        DataLoader,
        ImageFolderDataset,
        SequentialSampler,
    )

    rng = np.random.default_rng(2)
    for split in ["train", "val"]:
        for cls in ["class_a", "class_b"]:
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(3):
                arr = rng.integers(0, 256, size=(40, 48, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"img{i}.jpg")

    ds = ImageFolderDataset(str(tmp_path), "val", image_size=32)
    assert len(ds) == 6
    assert ds.class_to_idx == {"class_a": 0, "class_b": 1}
    img, label = ds[0]
    assert img.dtype == np.uint8  # normalization deferred to batch assembly

    loader = DataLoader(ds, batch_size=6, sampler=SequentialSampler(len(ds)))
    img_batch, labels = next(iter(loader))
    assert img_batch.dtype == np.float32
    assert img_batch.shape == (6, 32, 32, 3)
    # normalized: ImageNet mean/std applied (values roughly centered)
    assert -3.0 < img_batch.mean() < 3.0
    assert labels.tolist() == [0, 0, 0, 1, 1, 1]
