"""Space-to-depth stem (model.space_to_depth): exact equivalence + wiring.

The MLPerf ResNet trick (models/resnet.py): 2x2-pack the input and replace
the 7x7/2 stem with a folded 4x4/1 conv.  The fold is exact algebra, so the
oracle is strong: the SAME torch checkpoint ported into the standard and
the packed model must produce equal logits.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from test_torch_port import TorchBasicBlock, TorchResNet, _randomize_running_stats

from pytorch_distributed_training_tpu.models import get_model
from pytorch_distributed_training_tpu.models.resnet import fold_stem_kernel
from pytorch_distributed_training_tpu.models.torch_port import (
    import_torch_resnet_state_dict,
)


def test_folded_stem_matches_7x7_conv():
    """Direct algebra check: folded 4x4/1 conv over packed input == 7x7/2
    conv, including the boundary (padding) rows."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
    w7 = rng.standard_normal((7, 7, 3, 8)).astype(np.float32)

    ref = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w7), window_strides=(2, 2),
        padding=((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    b, h, w, c = x.shape
    z = x.reshape(b, h // 2, 2, w // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    z = z.reshape(b, h // 2, w // 2, 4 * c)
    out = jax.lax.conv_general_dilated(
        jnp.asarray(z), jnp.asarray(fold_stem_kernel(w7)),
        window_strides=(1, 1), padding=((2, 1), (2, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_s2d_resnet_matches_standard_from_same_checkpoint():
    """Port ONE torch ResNet-18 into both stems: logits must agree."""
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=10)
    _randomize_running_stats(tmodel, seed=1)
    sd = tmodel.state_dict()

    rng = np.random.default_rng(2)
    img = jnp.asarray(rng.standard_normal((4, 64, 64, 3)).astype(np.float32))

    outs = {}
    for s2d in (False, True):
        model = get_model("ResNet18", num_classes=10, space_to_depth=s2d)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
        if s2d:
            assert variables["params"]["conv1"]["kernel"].shape == (4, 4, 12, 64)
        variables = import_torch_resnet_state_dict(variables, sd)
        outs[s2d] = np.asarray(
            model.apply(
                {"params": variables["params"],
                 "batch_stats": variables["batch_stats"]},
                img, train=False,
            )
        )
    np.testing.assert_allclose(outs[True], outs[False], atol=1e-4, rtol=1e-4)


def test_s2d_init_folds_kaiming_draw():
    """From-scratch init: the packed kernel is a fold of a 7x7 kaiming draw
    (one all-zero slot per axis pair; matching total variance)."""
    model = get_model("ResNet18", num_classes=10, space_to_depth=True)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
        "params"
    ]
    k = np.asarray(params["conv1"]["kernel"])
    assert k.shape == (4, 4, 12, 64)
    # the (m=0, u=0) slots are structurally zero (only a=0/u=1 reaches m=0)
    assert np.all(k[0, :, 0:3] == 0) or np.all(k[0, :, 6:9] == 0)
    # 49 of 64 packed taps carry weight; nonzero count per output channel
    nonzero = np.count_nonzero(np.abs(k[..., 0]) > 0)
    assert nonzero == 49 * 3


def test_s2d_config_wiring(tmp_path):
    """Runner trains end-to-end with model.space_to_depth; ViT rejected."""
    from pytorch_distributed_training_tpu.engine import Runner

    def cfg(name, s2d=True):
        return {
            "dataset": {
                "name": "synthetic", "root": str(tmp_path), "n_classes": 4,
                "image_size": 32, "n_samples": 64,
            },
            "training": {
                "optimizer": {
                    "name": "SGD", "lr": 0.05, "weight_decay": 1.0e-4,
                    "momentum": 0.9,
                },
                "lr_schedule": {"name": "multi_step", "milestones": [4],
                                "gamma": 0.1},
                "train_iters": 2,
                "print_interval": 1,
                "val_interval": 2,
                "batch_size": 16,
                "num_workers": 2,
                "sync_bn": False,
            },
            "validation": {"batch_size": 16, "num_workers": 2},
            "model": {"name": name, "space_to_depth": s2d},
        }

    def run(c):
        runner = Runner(
            num_nodes=1, rank=0, seed=5, dist_url="tcp://127.0.0.1:9919",
            dist_backend="tpu", multiprocessing=False, logger_queue=None,
            global_cfg=c, tb_writer_constructor=lambda: None,
        )
        runner()
        return runner

    r = run(cfg("ResNet18"))
    assert r.iter == 2
    assert r.state.params["conv1"]["kernel"].shape == (4, 4, 12, 64)

    with pytest.raises(ValueError, match="ResNet family"):
        run(cfg("ViT-Ti16"))


def test_bn_stat_dtype_config(tmp_path):
    """model.bn_stat_dtype: bfloat16 trains end-to-end; bad values raise."""
    from pytorch_distributed_training_tpu.engine import Runner

    def cfg(**model_extra):
        return {
            "dataset": {
                "name": "synthetic", "root": str(tmp_path), "n_classes": 4,
                "image_size": 32, "n_samples": 64,
            },
            "training": {
                "optimizer": {
                    "name": "SGD", "lr": 0.05, "weight_decay": 1.0e-4,
                    "momentum": 0.9,
                },
                "lr_schedule": {"name": "multi_step", "milestones": [4],
                                "gamma": 0.1},
                "train_iters": 2,
                "print_interval": 1,
                "val_interval": 2,
                "batch_size": 16,
                "num_workers": 2,
                "sync_bn": False,
                "dtype": "bfloat16",
            },
            "validation": {"batch_size": 16, "num_workers": 2},
            "model": {"name": "ResNet18", **model_extra},
        }

    def run(c):
        runner = Runner(
            num_nodes=1, rank=0, seed=5, dist_url="tcp://127.0.0.1:9921",
            dist_backend="tpu", multiprocessing=False, logger_queue=None,
            global_cfg=c, tb_writer_constructor=lambda: None,
        )
        runner()
        return runner

    r = run(cfg(bn_stat_dtype="bfloat16"))
    assert r.iter == 2
    # running stats stay f32 regardless of the stat dtype
    assert r.state.batch_stats["bn1"]["mean"].dtype == jnp.float32

    with pytest.raises(ValueError, match="bn_stat_dtype must be"):
        run(cfg(bn_stat_dtype="float16"))
