"""Serving fault-tolerance oracles (serving/resilience.py + scheduler).

The two load-bearing oracles mirror the ISSUE acceptance criteria:

  - **Replay parity**: a request interrupted mid-decode by an injected
    device loss and resumed via hot-restart produces a token stream
    bitwise identical to an uninterrupted run — greedy AND sampled — on
    CPU.  The per-row per-token-index ``fold_in`` sampling keys plus
    re-feeding the generated tokens through the SAME decode program make
    this exact, not approximate.
  - **Poison isolation**: with ``serve_raise``/``serve_nan`` injected
    into one slot, exactly that request's future fails (with a diagnosed
    ``PoisonedRequestError``) while every other in-flight request
    completes token-identical to a clean run and the pool's free-block
    accounting returns to empty.

Every fault-scenario driver additionally asserts the KV pool's
accounting invariants after EVERY tick (``PagedKVPool.check_invariants``)
— a recovery path that leaks a block or a refcount fails at the tick it
leaks, not as an eventual pool exhaustion.
"""
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.engine import fault
from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_training_tpu.serving.resilience import (
    EngineRestartError,
    PoisonedRequestError,
)
from pytorch_distributed_training_tpu.serving.scheduler import ContinuousScheduler

VOCAB = 61


def small_lm(**kwargs):
    return TransformerLM(
        vocab_size=VOCAB, max_len=32, embed_dim=32, depth=2, num_heads=4, **kwargs
    )


@pytest.fixture(scope="module")
def lm_and_params():
    model = small_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _prompts(seed=3, lens=(2, 6, 4)):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, VOCAB, ln).astype(np.int32) for ln in lens]


def _make_sched(model, params, **kw):
    # prefix_cache off by default so ``blocks_in_use == 0`` is an exact
    # leak oracle (the cache legitimately retains prompt blocks after
    # retirement); the replay-parity tests turn it back on and compare
    # against a clean run's residual instead
    defaults = dict(
        slots=4, block_size=4, num_blocks=16, batch_buckets=[4],
        seq_buckets=[8], max_new_tokens=6, temperature=0.0, eos_id=None,
        prefix_cache=False, start=False,
    )
    defaults.update(kw)
    return ContinuousScheduler(model, params, **defaults)


def _drive(sched, futures, limit=200, check_pool=True):
    """Manual-tick driver; optionally asserts pool invariants per tick."""
    n = 0
    while any(not f.done() for f in futures):
        sched.tick()
        if check_pool:
            sched._kv.check_invariants()
        n += 1
        assert n < limit, "scheduler failed to drain"
    return n


def _run_under_spec(model, params, spec, **kw):
    fault.install(spec)
    try:
        sched = _make_sched(model, params, **kw)
        futs = [sched.submit(p) for p in _prompts()]
        _drive(sched, futs)
        return sched, futs
    finally:
        fault.install(None)


# --------------------------------------------------------------------- #
# acceptance oracle: replay parity


@pytest.mark.parametrize("temperature", [0.0, 0.8], ids=["greedy", "sampled"])
def test_replay_parity_after_device_loss(lm_and_params, temperature):
    """Interrupted-by-device-loss == uninterrupted, bitwise, per request."""
    model, params = lm_and_params
    clean_sched, clean = _run_under_spec(
        model, params, None, temperature=temperature, prefix_cache=True
    )
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_device_lost@3", temperature=temperature,
        prefix_cache=True,
    )
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result()["tokens"], ref[i])
    assert sched._supervisor.restarts() == 1
    snap = sched.metrics.snapshot()
    assert snap["engine_restarts"] == 1
    assert snap["replayed_tokens"] > 0
    assert snap.get("replay_parity_mismatch", 0) == 0
    # no leak beyond what a clean run's prefix cache legitimately retains
    assert sched._kv.blocks_in_use == clean_sched._kv.blocks_in_use


def test_replay_is_not_redelivered(lm_and_params):
    """on_token must not refire for tokens the client already holds."""
    model, params = lm_and_params
    streamed = []
    fault.install("serve_device_lost@3")
    try:
        sched = _make_sched(model, params)
        fut = sched.submit(_prompts()[1], on_token=streamed.append)
        _drive(sched, [fut])
    finally:
        fault.install(None)
    assert sched._supervisor.restarts() == 1
    # every token exactly once, in order, despite the mid-stream replay
    assert streamed == fut.result()["tokens"].tolist()


# --------------------------------------------------------------------- #
# acceptance oracle: poison isolation


def test_poison_isolation_decode_raise(lm_and_params):
    """serve_raise: exactly one future fails (diagnosed, cause chained),
    the rest are token-identical to a clean run, pool fully recycled."""
    model, params = lm_and_params
    _, clean = _run_under_spec(model, params, None, prefix_cache=False)
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_raise@2:1", prefix_cache=False
    )
    errs = [i for i, f in enumerate(futs) if f.exception() is not None]
    assert errs == [1]
    exc = futs[1].exception()
    assert isinstance(exc, PoisonedRequestError)
    assert "slot 1" in str(exc) and "tick 2" in str(exc)
    assert isinstance(exc.__cause__, fault.FaultInjectionError)
    for i in (0, 2):
        np.testing.assert_array_equal(futs[i].result()["tokens"], ref[i])
    assert sched._supervisor.restarts() == 0  # isolated, never restarted
    snap = sched.metrics.snapshot()
    assert snap["requests_poisoned"] == 1
    assert snap["poison_probes"] >= 2  # reproduce + bisect + confirm
    assert sched._kv.blocks_in_use == 0


def test_poison_isolation_nan_output_guard(lm_and_params):
    """serve_nan: the on-device isfinite guard evicts the NaN emitter
    with NO Python exception; other rows stay bit-exact."""
    model, params = lm_and_params
    _, clean = _run_under_spec(model, params, None, prefix_cache=False)
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_nan@2:0", prefix_cache=False
    )
    errs = [i for i, f in enumerate(futs) if f.exception() is not None]
    assert errs == [0]
    exc = futs[0].exception()
    assert isinstance(exc, PoisonedRequestError)
    assert "non-finite" in str(exc)
    assert exc.__cause__ is None  # guard path: nothing ever raised
    for i in (1, 2):
        np.testing.assert_array_equal(futs[i].result()["tokens"], ref[i])
    assert sched.metrics.snapshot()["requests_poisoned"] == 1
    assert sched._kv.blocks_in_use == 0


def test_poisoned_blocks_recycle_cleanly(lm_and_params):
    """A NaN-poisoned request's freed blocks must be reusable: requests
    admitted AFTER the eviction decode on recycled blocks bit-exactly."""
    model, params = lm_and_params
    model_ref, clean = _run_under_spec(model, params, None, prefix_cache=False)
    ref = [f.result()["tokens"] for f in clean]

    fault.install("serve_nan@2:0")
    try:
        # pool of 6 blocks: three 2-block requests fill it, so the late
        # request can only admit on the evicted request's recycled blocks
        sched = _make_sched(
            model, params, num_blocks=6, block_size=4, max_new_tokens=6,
            seq_buckets=[8], prefix_cache=False,
        )
        prompts = _prompts()
        futs = [sched.submit(p) for p in prompts]
        late = sched.submit(prompts[0])  # waits for blocks, then recycles
        _drive(sched, futs + [late])
    finally:
        fault.install(None)
    assert isinstance(futs[0].exception(), PoisonedRequestError)
    # the late request reuses the poisoned request's NaN-stained blocks
    # and still reproduces the clean tokens for the same prompt
    np.testing.assert_array_equal(late.result()["tokens"], ref[0])
    assert sched._kv.blocks_in_use == 0


def test_bisect_disabled_escalates_to_restart(lm_and_params):
    """poison_bisect=false with several suspects: the raise cannot be
    attributed, so each occurrence burns a restart — the documented cost
    of disabling isolation is that a PERSISTENT poison exhausts the
    budget and fails the world with the chained cause."""
    model, params = lm_and_params
    sched, futs = _run_under_spec(
        model, params, "serve_raise@2:1",
        resilience={"poison_bisect": False, "max_restarts": 1},
    )
    assert sched._supervisor.restarts() == 1
    assert sched._supervisor.exhausted()
    for f in futs:
        exc = f.exception()
        assert isinstance(exc, EngineRestartError)
        assert isinstance(exc.__cause__, fault.FaultInjectionError)
    # never probed: bisect was disabled
    assert sched.metrics.snapshot().get("poison_probes", 0) == 0
    assert sched._kv.blocks_in_use == 0


def test_single_suspect_evicted_without_probing(lm_and_params):
    """With exactly one active request there is nothing to bisect: it is
    evicted directly even when poison_bisect is disabled."""
    model, params = lm_and_params
    fault.install("serve_raise@2:0")
    try:
        sched = _make_sched(
            model, params, resilience={"poison_bisect": False}
        )
        fut = sched.submit(_prompts()[0])
        _drive(sched, [fut])
    finally:
        fault.install(None)
    assert isinstance(fut.exception(), PoisonedRequestError)
    assert sched._supervisor.restarts() == 0
    assert sched.metrics.snapshot().get("poison_probes", 0) == 0
    assert sched._kv.blocks_in_use == 0


# --------------------------------------------------------------------- #
# restart budget


def test_restart_budget_exhaustion_chains_cause(lm_and_params):
    model, params = lm_and_params
    sched, futs = _run_under_spec(
        model, params, "serve_device_lost@2;serve_device_lost@4",
        resilience={"max_restarts": 1},
    )
    for f in futs:
        exc = f.exception()
        assert isinstance(exc, EngineRestartError)
        assert isinstance(exc.__cause__, fault.DeviceLostError)
    assert sched._supervisor.exhausted()
    snap = sched.metrics.snapshot()
    assert snap["engine_restarts"] == 1
    assert snap["restart_budget_exhausted"] == 1
    assert snap["failed_inflight"] == 3
    assert sched._kv.blocks_in_use == 0  # _fail_inflight released them
    health = sched.health()
    assert health["live"] is False and health["ready"] is False


def test_resilience_config_rejects_unknown_keys(lm_and_params):
    model, params = lm_and_params
    with pytest.raises(ValueError, match="resilience"):
        _make_sched(model, params, resilience={"max_restart": 1})
    with pytest.raises(ValueError, match="watchdog"):
        _make_sched(model, params, resilience={"watchdog": {"factr": 2.0}})


# --------------------------------------------------------------------- #
# satellite: deadline enforcement for admission-waiting requests


def test_admission_wait_deadline_swept_manual(lm_and_params):
    """A request parked in pool-admission WAIT expires at its deadline."""
    model, params = lm_and_params
    rng = np.random.default_rng(6)
    # each request: 8 + 4 tokens -> 3 blocks of a 4-block pool, so the
    # second stays queued while the first runs
    sched = _make_sched(
        model, params, slots=2, num_blocks=4, max_new_tokens=4,
        batch_buckets=[2], prefix_cache=False,
    )
    f1 = sched.submit(rng.integers(2, VOCAB, 8).astype(np.int32))
    f2 = sched.submit(
        rng.integers(2, VOCAB, 8).astype(np.int32), deadline_ms=30.0
    )
    sched.tick()  # admits f1, parks f2 (admission_waits)
    sched._kv.check_invariants()
    assert sched.metrics.snapshot()["admission_waits"] >= 1
    time.sleep(0.05)  # let f2's deadline lapse while it is still waiting
    _drive(sched, [f1, f2])
    assert f1.result()["gen_len"] == 4
    assert isinstance(f2.exception(), TimeoutError)
    assert sched.metrics.snapshot()["timeouts"] == 1
    assert sched._kv.blocks_in_use == 0


def test_admission_wait_deadline_swept_threaded(lm_and_params):
    """Regression: the background loop must sweep a blocked request AT
    its deadline even though no new submit arrives to trigger a sweep."""
    model, params = lm_and_params
    rng = np.random.default_rng(6)
    sched = ContinuousScheduler(
        model, params, slots=2, block_size=4, num_blocks=4,
        batch_buckets=[2], seq_buckets=[8], max_new_tokens=4,
        temperature=0.0, eos_id=None, prefix_cache=False, start=True,
    )
    with sched:
        f1 = sched.submit(rng.integers(2, VOCAB, 8).astype(np.int32))
        f2 = sched.submit(
            rng.integers(2, VOCAB, 8).astype(np.int32), deadline_ms=1.0
        )
        assert f1.result(timeout=60)["gen_len"] == 4
        with pytest.raises(TimeoutError):
            f2.result(timeout=60)


# --------------------------------------------------------------------- #
# satellite: retry telemetry


def test_retry_attempts_and_exhaustion_counted():
    from pytorch_distributed_training_tpu.telemetry.registry import get_registry
    from pytorch_distributed_training_tpu.utils.retry import Retry

    reg = get_registry()
    a0 = reg.counters().get("retry_attempts", 0)
    e0 = reg.counters().get("retry_exhausted", 0)

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    policy = Retry(attempts=3, backoff=0.0, sleep=lambda d: None)
    assert policy.call(flaky) == "ok"
    assert reg.counters()["retry_attempts"] == a0 + 2
    assert reg.counters().get("retry_exhausted", 0) == e0

    def doomed():
        raise OSError("permanent")

    with pytest.raises(OSError):
        policy.call(doomed)
    assert reg.counters()["retry_exhausted"] == e0 + 1
    assert reg.counters()["retry_attempts"] == a0 + 4  # 2 more before exhaustion


# --------------------------------------------------------------------- #
# satellite: close/drain lifecycle


def test_close_under_concurrent_submit_race(lm_and_params):
    """close() vs late submit: in-flight work drains, late submissions
    get a clean RuntimeError, nothing deadlocks, and a ServingMetrics
    snapshot taken DURING close stays coherent."""
    model, params = lm_and_params
    sched = ContinuousScheduler(
        model, params, slots=2, block_size=4, num_blocks=16,
        batch_buckets=[2], seq_buckets=[8], max_new_tokens=3,
        temperature=0.0, eos_id=None, prefix_cache=False, start=True,
    )
    prompts = _prompts(seed=9, lens=(3, 5))
    futs = [sched.submit(p) for p in prompts]
    snaps, rejected = [], []

    def late_submitter():
        for _ in range(200):
            snaps.append(sched.metrics.snapshot())
            try:
                futs.append(sched.submit(prompts[0]))
            except RuntimeError:
                rejected.append(1)
                return

    t = threading.Thread(target=late_submitter)
    t.start()
    sched.close()
    t.join(timeout=30)
    assert not t.is_alive()
    assert rejected, "submitter never observed the closed scheduler"
    for f in futs:  # everything accepted before close must resolve
        assert f.result(timeout=60)["gen_len"] == 3
    assert sched._kv.blocks_in_use == 0
    assert all(isinstance(s, dict) for s in snaps)


def test_drain_finishes_inflight_then_closes(lm_and_params):
    model, params = lm_and_params
    sched = _make_sched(model, params)
    futs = [sched.submit(p) for p in _prompts()]
    sched.tick()
    ms = sched.drain()
    assert ms >= 0.0
    for f in futs:
        assert f.result()["gen_len"] == 6
    assert sched._kv.blocks_in_use == 0
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit(_prompts()[0])
    assert sched.drain() == 0.0  # idempotent once closed


def test_drain_rejects_submissions_while_draining(lm_and_params):
    model, params = lm_and_params
    sched = _make_sched(model, params)
    with sched._cond:
        sched._draining = True
    with pytest.raises(RuntimeError, match="draining"):
        sched.submit(_prompts()[0])
    with sched._cond:
        sched._draining = False
    sched.close()


def test_drain_deadline_bounds_shutdown(lm_and_params):
    """Past the drain deadline the remainder fails with TimeoutError —
    the drain completes instead of hanging on slow work."""
    model, params = lm_and_params
    sched = _make_sched(model, params)
    futs = [sched.submit(p) for p in _prompts()]
    sched.tick()
    sched._kv.check_invariants()
    ms = sched.drain(deadline_ms=0.001)  # lapses before the next tick
    assert ms >= 0.0
    for f in futs:
        assert isinstance(f.exception(), TimeoutError)
    assert sched.metrics.snapshot()["drain_expired"] == 1
    assert sched._kv.blocks_in_use == 0
    sched._kv.check_invariants()


def test_serve_nan_poison_during_drain(lm_and_params):
    """Compound #3 (chaos soak): a poison fault that fires INSIDE the
    drain(deadline_ms) window.  The drain loop must run the full bisect/
    evict ladder mid-shutdown — exactly one future fails diagnosed, every
    other request still completes, and the pool drains to empty."""
    model, params = lm_and_params
    fault.reset_counters()  # the registry is global; earlier tests leak
    sched = _make_sched(model, params)
    try:
        futs = [sched.submit(p) for p in _prompts()]
        sched.tick()  # admit; everything else happens inside drain()
        fault.install(f"serve_nan@{sched._tick_no + 2}:0")
        ms = sched.drain(deadline_ms=60_000)
        assert ms >= 0.0
        errs = [i for i, f in enumerate(futs) if f.exception() is not None]
        assert errs == [0]
        assert isinstance(futs[0].exception(), PoisonedRequestError)
        for i in (1, 2):
            assert futs[i].result()["gen_len"] == 6
        snap = sched.metrics.snapshot()
        assert snap["requests_poisoned"] == 1
        c = fault.counters()
        assert c.get("injected_serve_nans") == 1
        assert c.get("fault_fired_serve_nan") == 1
        assert sched._kv.blocks_in_use == 0
        sched._kv.check_invariants()
    finally:
        fault.install(None)
        fault.reset_counters()


def test_unfired_serve_fault_reported_at_close(lm_and_params):
    """A fault armed for a tick the engine never reaches (queue empties
    first) must not vanish: close() reports it via ``fault_unfired_*`` so
    the soak accounting oracle sees exactly fired-or-reported-unfired."""
    model, params = lm_and_params
    fault.reset_counters()  # the registry is global; earlier tests leak
    fault.install("serve_nan@999:0")
    try:
        sched = _make_sched(model, params)
        futs = [sched.submit(p) for p in _prompts()]
        _drive(sched, futs)
        for f in futs:
            assert f.result()["gen_len"] == 6  # fault never fired
        assert fault.get_injector().pending() == {"serve_nan": [999]}
        sched.close()
        c = fault.counters()
        assert c.get("fault_unfired_serve_nan") == 1
        assert not c.get("injected_serve_nans")
    finally:
        fault.install(None)
        fault.reset_counters()


def test_threaded_drain_under_load(lm_and_params):
    model, params = lm_and_params
    sched = ContinuousScheduler(
        model, params, slots=2, block_size=4, num_blocks=16,
        batch_buckets=[2], seq_buckets=[8], max_new_tokens=4,
        temperature=0.0, eos_id=None, prefix_cache=False, start=True,
    )
    futs = [sched.submit(p) for p in _prompts(seed=11, lens=(4, 3, 6, 2))]
    ms = sched.drain()
    assert ms >= 0.0
    for f in futs:
        assert f.result(timeout=1)["gen_len"] == 4
    assert sched._kv.blocks_in_use == 0


# --------------------------------------------------------------------- #
# health + SIGTERM


def test_health_snapshot_and_gauge_mirror(lm_and_params):
    model, params = lm_and_params
    sched = _make_sched(model, params, resilience={"max_restarts": 5})
    h = sched.health()
    assert h["ready"] is True and h["live"] is True
    assert h["queue_depth"] == 0 and h["active_slots"] == 0
    assert h["engine_restarts"] == 0 and h["restart_budget"] == 5
    assert h["last_tick_age_s"] is None  # no tick yet

    fut = sched.submit(_prompts()[0])
    sched.tick()
    h = sched.health()
    assert h["active_slots"] == 1
    assert h["last_tick_age_s"] is not None and h["last_tick_age_s"] >= 0.0
    snap = sched.metrics.snapshot()
    assert snap["health_ready"] == 1.0
    assert snap["health_active_slots"] == 1.0
    _drive(sched, [fut])
    sched.close()
    assert sched.health()["ready"] is False


def test_sigterm_handler_triggers_drain(lm_and_params):
    """install_drain_handler routes SIGTERM to drain; invoked directly
    (in-process kill would tear down the test runner)."""
    from pytorch_distributed_training_tpu.serving.engine import InferenceEngine

    cfg = {
        "dataset": {"name": "synthetic_text", "n_classes": VOCAB},
        "model": {
            "name": "TransformerLM", "embed_dim": 32, "depth": 2,
            "num_heads": 4, "max_len": 32,
        },
        "serving": {
            "dtype": "float32", "max_batch_size": 2, "max_delay_ms": 5,
            "batch_buckets": [2], "seq_buckets": [8], "max_new_tokens": 3,
            "temperature": 0.0, "eos_id": None, "seed": 0,
            "scheduler": {
                "enabled": True, "slots": 2, "block_size": 4,
                "num_blocks": 16,
            },
            "resilience": {"max_restarts": 2, "drain_deadline_ms": 30000},
        },
    }
    prev = signal.getsignal(signal.SIGTERM)
    try:
        engine = InferenceEngine.from_config(cfg)
        engine.install_drain_handler()
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler) and handler is not prev
        fut = engine.submit(np.asarray([5, 9, 13], np.int32))
        handler(signal.SIGTERM, None)  # what the kernel would deliver
        assert fut.result(timeout=60)["gen_len"] == 3
        deadline = time.monotonic() + 30
        while not engine.health()["closed"]:
            assert time.monotonic() < deadline, "drain never closed the engine"
            time.sleep(0.01)
        assert engine.health()["ready"] is False
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_engine_rejects_resilience_on_batcher_path():
    from pytorch_distributed_training_tpu.serving.engine import InferenceEngine

    cfg = {
        "dataset": {"name": "synthetic_text", "n_classes": VOCAB},
        "model": {
            "name": "TransformerLM", "embed_dim": 32, "depth": 2,
            "num_heads": 4, "max_len": 32,
        },
        "serving": {
            "dtype": "float32", "max_batch_size": 2, "max_delay_ms": 5,
            "batch_buckets": [2], "seq_buckets": [8], "max_new_tokens": 3,
            "seed": 0,
            "resilience": {"max_restarts": 2},  # without scheduler.enabled
        },
    }
    with pytest.raises(ValueError, match="resilience"):
        InferenceEngine.from_config(cfg)


# --------------------------------------------------------------------- #
# watchdog: hung tick -> diagnosed restart


def test_hung_tick_becomes_diagnosed_restart(lm_and_params):
    """serve_hang stalls one tick past the watchdog limit; the fire is
    converted into a HungTickError -> hot-restart, and the rebuilt
    engine still finishes every request bitwise-identically."""
    model, params = lm_and_params
    _, clean = _run_under_spec(model, params, None)
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_hang@5:0.5",
        resilience={
            "watchdog": {
                "enabled": True, "min_seconds": 0.15, "factor": 4.0,
                "warmup": 3, "poll_seconds": 0.02,
            },
        },
    )
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result()["tokens"], ref[i])
    assert sched._supervisor.restarts() == 1
    snap = sched.metrics.snapshot()
    assert snap["serve_watchdog_fires"] >= 1
    assert snap["engine_restarts"] == 1
    sched.close()


# --------------------------------------------------------------------- #
# poison isolation under the async decode pipeline (async_depth > 0):
# the finite guard / poison shim fire up to async_depth ticks AFTER the
# faulted dispatch, so eviction happens at DRAIN time — attribution must
# still name exactly the poisoned request, and the lagged retire must
# not leak blocks or disturb neighbours.


@pytest.mark.parametrize("depth", [1, 2])
def test_async_poison_isolation_nan_output_guard(lm_and_params, depth):
    """serve_nan with a full dispatch ring: the non-finite flag is
    observed one-or-more ticks late at drain, evicts ONLY the poisoned
    slot, and the survivors stay bitwise equal to a SYNC clean run."""
    model, params = lm_and_params
    _, clean = _run_under_spec(model, params, None, prefix_cache=False)
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_nan@2:0", prefix_cache=False,
        async_depth=depth,
    )
    errs = [i for i, f in enumerate(futs) if f.exception() is not None]
    assert errs == [0]
    exc = futs[0].exception()
    assert isinstance(exc, PoisonedRequestError)
    assert "non-finite" in str(exc)
    assert exc.__cause__ is None  # guard path: nothing ever raised
    for i in (1, 2):
        np.testing.assert_array_equal(futs[i].result()["tokens"], ref[i])
    assert sched._supervisor.restarts() == 0
    assert sched.metrics.snapshot()["requests_poisoned"] == 1
    assert sched._kv.blocks_in_use == 0


@pytest.mark.parametrize("depth", [1, 2])
def test_async_poison_isolation_decode_raise(lm_and_params, depth):
    """serve_raise mid-pipeline: the supervisor drains the in-flight
    ring (flush_async) BEFORE bisecting, so the sync probe sees a
    state-consistent pool and convicts exactly the faulted request."""
    model, params = lm_and_params
    _, clean = _run_under_spec(model, params, None, prefix_cache=False)
    ref = [f.result()["tokens"] for f in clean]

    sched, futs = _run_under_spec(
        model, params, "serve_raise@2:1", prefix_cache=False,
        async_depth=depth,
    )
    errs = [i for i, f in enumerate(futs) if f.exception() is not None]
    assert errs == [1]
    exc = futs[1].exception()
    assert isinstance(exc, PoisonedRequestError)
    assert isinstance(exc.__cause__, fault.FaultInjectionError)
    for i in (0, 2):
        np.testing.assert_array_equal(futs[i].result()["tokens"], ref[i])
    assert sched._supervisor.restarts() == 0  # isolated, never restarted
    snap = sched.metrics.snapshot()
    assert snap["requests_poisoned"] == 1
    assert sched._kv.blocks_in_use == 0
