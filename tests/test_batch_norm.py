"""DistributedBatchNorm parity vs torch.nn.BatchNorm2d + SyncBN semantics.

The sync test is the SURVEY.md §4 prescription: global-batch stats on N fake
devices must equal single-device full-batch stats.
"""
import pytest
import functools

import jax
import jax.numpy as jnp
import numpy as np
import torch

from pytorch_distributed_training_tpu.ops import DistributedBatchNorm


def _torch_bn_step(x_nchw, training=True, steps=1):
    bn = torch.nn.BatchNorm2d(x_nchw.shape[1], eps=1e-5, momentum=0.1)
    bn.train(training)
    with torch.no_grad():
        for _ in range(steps):
            out = bn(torch.tensor(x_nchw))
    return (
        out.numpy(),
        bn.running_mean.numpy(),
        bn.running_var.numpy(),
    )


@pytest.mark.quick
def test_train_mode_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 5, 6, 3)).astype(np.float32)  # NHWC
    x_nchw = np.transpose(x, (0, 3, 1, 2))

    ref_out, ref_mean, ref_var = _torch_bn_step(x_nchw, training=True, steps=1)

    bn = DistributedBatchNorm(use_running_average=False)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out, updated = bn.apply(variables, jnp.asarray(x), mutable=["batch_stats"])

    np.testing.assert_allclose(
        np.transpose(np.asarray(out), (0, 3, 1, 2)), ref_out, rtol=1e-4, atol=1e-5
    )
    # Running stats: torch uses UNBIASED batch var for the running update.
    np.testing.assert_allclose(
        np.asarray(updated["batch_stats"]["mean"]), ref_mean, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(updated["batch_stats"]["var"]), ref_var, rtol=1e-5, atol=1e-6
    )


def test_eval_mode_uses_running_stats():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 3, 3, 2)).astype(np.float32)
    bn = DistributedBatchNorm(use_running_average=True)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out = bn.apply(variables, jnp.asarray(x))
    # fresh running stats are mean 0 var 1 -> output ~= input (eps only)
    np.testing.assert_allclose(np.asarray(out), x / np.sqrt(1 + 1e-5), rtol=1e-5)


@pytest.mark.quick
def test_sync_bn_equals_full_batch():
    """N-device synced stats == 1-device full-batch stats (SyncBatchNorm parity)."""
    n_dev = jax.device_count()
    assert n_dev >= 4, "conftest must provide 8 virtual devices"
    rng = np.random.default_rng(2)
    full = rng.normal(size=(16, 4, 4, 3)).astype(np.float32)

    # Single-device full-batch reference.
    bn_local = DistributedBatchNorm(use_running_average=False)
    variables = bn_local.init(jax.random.PRNGKey(0), jnp.asarray(full))
    ref_out, ref_updated = bn_local.apply(
        variables, jnp.asarray(full), mutable=["batch_stats"]
    )

    # Sharded: per-device shard of the batch, axis_name sync.
    bn_sync = DistributedBatchNorm(use_running_average=False, axis_name="data")
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(jax.sharding.PartitionSpec(), jax.sharding.PartitionSpec("data")),
        out_specs=(jax.sharding.PartitionSpec("data"), jax.sharding.PartitionSpec()),
    )
    def sharded_apply(variables, x):
        out, updated = bn_sync.apply(variables, x, mutable=["batch_stats"])
        return out, updated["batch_stats"]

    out, stats = sharded_apply(variables, jnp.asarray(full))

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats["mean"]),
        np.asarray(ref_updated["batch_stats"]["mean"]),
        rtol=1e-5, atol=1e-6,
    )
    # Note: sync running-var uses the GLOBAL element count for the unbiased
    # correction (like torch SyncBatchNorm), so it matches full-batch exactly.
    np.testing.assert_allclose(
        np.asarray(stats["var"]),
        np.asarray(ref_updated["batch_stats"]["var"]),
        rtol=1e-5, atol=1e-6,
    )


def test_momentum_accumulation_matches_torch():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 5, 6, 3)).astype(np.float32)
    x_nchw = np.transpose(x, (0, 3, 1, 2))
    _, ref_mean, ref_var = _torch_bn_step(x_nchw, training=True, steps=3)

    bn = DistributedBatchNorm(use_running_average=False)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    for _ in range(3):
        _, updated = bn.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
        variables = {"params": variables["params"], **updated}

    np.testing.assert_allclose(
        np.asarray(variables["batch_stats"]["mean"]), ref_mean, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(variables["batch_stats"]["var"]), ref_var, rtol=1e-5, atol=1e-6
    )


def test_sync_bn_bf16_stats_shifted_moments():
    """Low-precision sync stats (stat_dtype=bf16) use SHIFTED moments before
    the pmean (ADVICE r3 #4): with a large common activation offset the raw
    E[x^2]-mean^2 form cancels catastrophically in bf16, the shifted form
    stays within bf16 resolution of the f32 stats."""
    rng = np.random.default_rng(4)
    # big common mean (post-ReLU-like), small variance: the cancellation trap
    full = (8.0 + 0.1 * rng.normal(size=(16, 4, 4, 3))).astype(np.float32)

    def run(stat_dtype):
        bn = DistributedBatchNorm(
            use_running_average=False, axis_name="data", stat_dtype=stat_dtype
        )
        # init with a LOCAL twin: the sync module's pmean needs the mapped
        # axis in scope, which exists only inside the shard_map below
        variables = DistributedBatchNorm(use_running_average=False).init(
            jax.random.PRNGKey(0), jnp.asarray(full)
        )
        # running mean near the activation level => a useful shift center
        variables = {
            "params": variables["params"],
            "batch_stats": {
                "mean": jnp.full((3,), 8.0, jnp.float32),
                "var": jnp.full((3,), 0.01, jnp.float32),
            },
        }
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))

        @functools.partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                jax.sharding.PartitionSpec(),
                jax.sharding.PartitionSpec("data"),
            ),
            out_specs=(
                jax.sharding.PartitionSpec("data"),
                jax.sharding.PartitionSpec(),
            ),
        )
        def apply(variables, x):
            out, updated = bn.apply(variables, x, mutable=["batch_stats"])
            return out, updated["batch_stats"]

        return apply(variables, jnp.asarray(full))

    out32, stats32 = run(None)
    out16, stats16 = run(jnp.bfloat16)
    # all finite, variance non-negative
    assert np.isfinite(np.asarray(out16)).all()
    assert (np.asarray(stats16["var"]) >= 0).all()
    # bf16 resolution at var ~0.01 is ~1e-4; the UNSHIFTED bf16 form would
    # be off by O(var) itself (8^2=64 rounds at 0.25 granularity in bf16)
    np.testing.assert_allclose(
        np.asarray(stats16["var"]), np.asarray(stats32["var"]),
        rtol=0.1, atol=2e-3,
    )
    # the OUTPUT carries bf16 input quantization (x~8.0 has 0.03 resolution
    # in bf16, ~30% of the 0.1 deviations being normalized) — that error is
    # the documented model.bn_stat_dtype hazard, not the moments'; the
    # shifted moments above are what this test pins.  Ballpark sanity only:
    np.testing.assert_allclose(
        np.asarray(out16), np.asarray(out32), atol=0.6
    )
