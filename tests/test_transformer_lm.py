"""Long-context LM: sequence-parallel training step vs single-shard reference.

The strongest correctness property of the SP design (engine/sp_steps.py):
one DP x SP step on the (data=2, sequence=4) fake-device mesh must produce
the SAME loss and updated parameters as a single-device step of the same
model over the full (unsharded) batch — ring attention, position-embedding
slicing, partial-loss psum, and the uniform gradient psum all have to be
exact for this to hold.
"""
import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.engine import TrainState, build_lm_train_step
from pytorch_distributed_training_tpu.engine.sp_steps import lm_loss_local
from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import make_sp_mesh, replicated_sharding
from pytorch_distributed_training_tpu.schedulers import multi_step_lr

VOCAB, SEQ, BATCH = 64, 32, 4


def _data(seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, VOCAB, (BATCH, SEQ + 1)).astype(np.int32)
    return jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:])  # host shift


def _model(seq_axis):
    return TransformerLM(
        vocab_size=VOCAB, max_len=SEQ, embed_dim=32, depth=2, num_heads=4,
        seq_axis=seq_axis,
    )


def test_single_shard_forward():
    model = _model(None)
    tokens, _ = _data()
    vars_ = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(vars_, tokens)
    assert logits.shape == (BATCH, SEQ, VOCAB)


def test_sp_step_matches_single_device():
    tokens, labels = _data()
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)

    # ---- single-device reference ------------------------------------------
    ref_model = _model(None)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)["params"]

    def ref_loss(p):
        logits = ref_model.apply({"params": p}, tokens)
        return lm_loss_local(logits, labels, labels.size)

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    params_ref, _ = opt.update(grads_ref, opt.init(params), params, 0.05)

    # ---- DP(2) x SP(4) sharded step ---------------------------------------
    mesh = make_sp_mesh(sequence_parallelism=4)
    sp_model = _model("sequence")
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_lm_train_step(sp_model, opt, lr_fn, mesh)
    state2, loss_sp = step(state, tokens, labels)

    assert np.isclose(float(loss_sp), float(loss_ref), atol=1e-5), (loss_sp, loss_ref)
    flat_ref = jax.tree_util.tree_leaves(params_ref)
    flat_sp = jax.tree_util.tree_leaves(state2.params)
    for a, b in zip(flat_ref, flat_sp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_sp_step_ulysses_matches_single_device():
    tokens, labels = _data(seed=3)
    opt = SGD(lr=0.05, momentum=0.9)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    ref_model = _model(None)
    params = ref_model.init(jax.random.PRNGKey(1), tokens)["params"]

    def ref_loss(p):
        logits = ref_model.apply({"params": p}, tokens)
        return lm_loss_local(logits, labels, labels.size)

    # param-level oracle too (ADVICE.md r1: loss-only would miss a wrong
    # all_to_all transpose in the ulysses backward)
    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    params_ref, _ = opt.update(grads_ref, opt.init(params), params, 0.05)

    mesh = make_sp_mesh(sequence_parallelism=4)
    sp_model = TransformerLM(
        vocab_size=VOCAB, max_len=SEQ, embed_dim=32, depth=2, num_heads=4,
        seq_axis="sequence", seq_impl="ulysses",
    )
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_lm_train_step(sp_model, opt, lr_fn, mesh)
    state2, loss_sp = step(state, tokens, labels)
    assert np.isclose(float(loss_sp), float(loss_ref), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_ref),
        jax.tree_util.tree_leaves(state2.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)
