"""Engine: compiled SPMD train/eval steps on the 8-device mesh + full Runner.

This is the "minimum end-to-end slice" oracle (SURVEY.md §7 stage 3): the
test-sync config semantics with a synthetic dataset, real pjit/shard_map
collectives on fake devices.
"""
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.engine import (
    Runner,
    build_eval_step,
    build_train_step,
    init_train_state,
)
from pytorch_distributed_training_tpu.models import get_model
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_training_tpu.schedulers import multi_step_lr


def _tiny_setup(sync_bn: bool, n_classes: int = 8):
    mesh = make_mesh()
    model = get_model(
        "ResNet18", num_classes=n_classes, axis_name=DATA_AXIS if sync_bn else None
    )
    opt = SGD(lr=0.001, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.001, [1000], 0.1)
    state = init_train_state(
        model, opt, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    train_step = build_train_step(model, opt, lr_fn, mesh, sync_bn=sync_bn)
    eval_step = build_eval_step(model, mesh)
    return mesh, state, train_step, eval_step


def _batch(mesh, rng, batch=64, n_classes=8):
    img = rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
    label = (rng.integers(0, n_classes, (batch,))).astype(np.int32)
    # class-dependent signal so a few steps of training measurably help
    img += 0.5 * label[:, None, None, None] / n_classes
    g_img = jax.device_put(img, batch_sharding(mesh, 4))
    g_label = jax.device_put(label, batch_sharding(mesh, 1))
    return g_img, g_label


@pytest.mark.parametrize("sync_bn", [True, False])
def test_train_step_decreases_loss(sync_bn):
    mesh, state, train_step, _ = _tiny_setup(sync_bn)
    rng = np.random.default_rng(0)
    img, label = _batch(mesh, rng)
    losses = []
    for _ in range(12):
        state, loss = train_step(state, img, label)
        losses.append(float(loss))
    assert int(state.step) == 12
    assert min(losses[-3:]) < losses[0], losses
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_train_state_stays_replicated():
    mesh, state, train_step, _ = _tiny_setup(sync_bn=True)
    rng = np.random.default_rng(1)
    img, label = _batch(mesh, rng)
    state, _ = train_step(state, img, label)
    # params remain fully-replicated across the mesh after the update
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated
    bs_leaf = jax.tree.leaves(state.batch_stats)[0]
    assert bs_leaf.sharding.is_fully_replicated


@pytest.mark.quick
def test_sync_bn_stats_update_in_train_step():
    mesh, state, train_step, _ = _tiny_setup(sync_bn=True)
    before = jax.tree.map(np.asarray, state.batch_stats)
    rng = np.random.default_rng(2)
    img, label = _batch(mesh, rng)
    state, _ = train_step(state, img, label)
    after = jax.tree.map(np.asarray, state.batch_stats)
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b), before, after)
    assert any(jax.tree.leaves(changed))


@pytest.mark.quick
def test_dp_step_matches_single_device():
    """8-device DP + SyncBN step == single-device full-batch step.

    The DDP-parity oracle: gradient averaging, SyncBN statistics, and the
    SGD update must all compose to exactly the single-device result.  In
    particular this pins the gradient scale — shard_map's AD transpose
    already psums the replicated params' cotangent, so an extra post-grad
    pmean/psum would make grads world_size x too large (caught here).
    """
    opt = SGD(lr=0.01, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.01, [1000], 0.1)
    model = get_model("ResNet18", num_classes=8, axis_name=DATA_AXIS)
    state0 = init_train_state(
        model, opt, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
    )
    rng = np.random.default_rng(7)
    img = rng.standard_normal((16, 32, 32, 3)).astype(np.float32)
    label = rng.integers(0, 8, (16,)).astype(np.int32)

    mesh8 = make_mesh()
    step8 = build_train_step(model, opt, lr_fn, mesh8, sync_bn=True, donate=False)
    s8 = jax.device_put(state0, replicated_sharding(mesh8))
    s8, loss8 = step8(
        s8,
        jax.device_put(img, batch_sharding(mesh8, 4)),
        jax.device_put(label, batch_sharding(mesh8, 1)),
    )

    mesh1 = make_mesh(devices=jax.devices()[:1])
    step1 = build_train_step(model, opt, lr_fn, mesh1, sync_bn=True, donate=False)
    s1 = jax.device_put(state0, replicated_sharding(mesh1))
    s1, loss1 = step1(
        s1,
        jax.device_put(img, batch_sharding(mesh1, 4)),
        jax.device_put(label, batch_sharding(mesh1, 1)),
    )

    assert np.isclose(float(loss8), float(loss1), atol=1e-5)
    for a, b in zip(jax.tree.leaves(s8.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
    for a, b in zip(jax.tree.leaves(s8.batch_stats), jax.tree.leaves(s1.batch_stats)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_eval_step_metrics_sane():
    mesh, state, train_step, eval_step = _tiny_setup(sync_bn=True)
    rng = np.random.default_rng(3)
    img, label = _batch(mesh, rng)
    loss, acc1, acc5 = eval_step(state, img, label)
    assert np.isfinite(float(loss))
    assert 0.0 <= float(acc1) <= 100.0
    assert float(acc5) >= float(acc1)


def _tiny_cfg(tmp_path):
    return {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 8,
            "image_size": 32,
            "n_samples": 128,
        },
        "training": {
            "optimizer": {"name": "SGD", "lr": 0.05, "weight_decay": 1.0e-4, "momentum": 0.9},
            "lr_schedule": {"name": "multi_step", "milestones": [4], "gamma": 0.1},
            "train_iters": 6,
            "print_interval": 2,
            "val_interval": 3,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": True,
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ResNet18"},
    }


def test_runner_end_to_end(tmp_path):
    """The reference flow end-to-end: Runner -> worker -> train loop -> val.

    Mirrors cold-start call stack SURVEY.md §3.1 on the 8-device CPU mesh.
    """

    class _FakeTB:
        def __init__(self):
            self.scalars = []

        def add_scalar(self, tag, value, step):
            self.scalars.append((tag, value, step))

    tb = _FakeTB()
    runner = Runner(
        num_nodes=1,
        rank=0,
        seed=1029,
        dist_url="tcp://127.0.0.1:9901",
        dist_backend="tpu",
        multiprocessing=True,
        logger_queue=None,
        global_cfg=_tiny_cfg(tmp_path),
        tb_writer_constructor=lambda: tb,
    )
    runner()

    assert runner.iter == 6
    tags = {t for t, _, _ in tb.scalars}
    # the reference's exact five tag families (train_distributed.py:295-297, :329-331)
    assert {"loss/train", "lr_group/0", "eval/Acc@1", "eval/Acc@5", "eval/loss"} <= tags
    # val ran at iters 2 and 5 (is_val semantics :255-259)
    val_iters = sorted(s for t, _, s in tb.scalars if t == "eval/Acc@1")
    assert val_iters == [2, 5]
    train_losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert all(np.isfinite(v) for v in train_losses)
    # world: all 8 fake devices participate
    assert runner.world_size == 8
    assert runner.global_batch == 16


def test_exact_eval_matches_unsharded():
    """validation.exact (round 5): the masked-sum eval over wrap-padded,
    ragged batches equals the unsharded full-set metrics EXACTLY on a
    deliberately non-divisible val set (N=37, 2 emulated hosts, batch 16;
    the parity eval double-counts the tail — reference
    train_distributed.py:219-222)."""
    from pytorch_distributed_training_tpu.data import DistributedShardSampler
    from pytorch_distributed_training_tpu.engine import build_eval_step_exact

    mesh, state, _, _ = _tiny_setup(sync_bn=False)
    model = get_model("ResNet18", num_classes=8)
    rng = np.random.default_rng(11)
    n_val, host_batch, n_hosts = 37, 16, 2
    imgs = rng.standard_normal((n_val, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 8, (n_val,)).astype(np.int32)

    # ---- unsharded reference over exactly the 37 samples ------------------
    params = jax.device_get(state.params)
    out = model.apply(
        {"params": params, "batch_stats": jax.device_get(state.batch_stats)},
        jnp.asarray(imgs), train=False,
    )
    logp = jax.nn.log_softmax(np.asarray(out, np.float32), axis=-1)
    ce_ref = float(np.mean([-logp[i, labels[i]] for i in range(n_val)]))
    top5 = np.asarray(jax.lax.top_k(out, 5)[1])
    acc1_ref = 100.0 * np.mean(top5[:, 0] == labels)
    acc5_ref = 100.0 * np.mean((top5 == labels[:, None]).any(axis=1))

    # ---- exact eval: 2 emulated hosts, wrap-padded sampler, ragged batches
    step = build_eval_step_exact(model, mesh)
    totals = np.zeros(4, np.float64)
    for rank in range(n_hosts):
        sampler = DistributedShardSampler(
            n_val, num_replicas=n_hosts, rank=rank, shuffle=False
        )
        local = sampler.local_indices()
        assert len(local) == 19  # ceil(37/2): rank 1 carries a wrap dup
        n_real = -(-(n_val - rank) // n_hosts)
        for lo in range(0, len(local), host_batch):
            idx = local[lo:lo + host_batch]
            b = len(idx)
            img = imgs[idx]
            lab = labels[idx]
            mask = (np.arange(lo, lo + b) < n_real).astype(np.int32)
            if b < host_batch:
                pad = host_batch - b
                img = np.concatenate([img, np.repeat(img[-1:], pad, axis=0)])
                lab = np.concatenate([lab, np.zeros(pad, lab.dtype)])
                mask = np.concatenate([mask, np.zeros(pad, np.int32)])
            sums = step(state, jnp.asarray(img), jnp.asarray(lab), jnp.asarray(mask))
            totals += np.asarray([float(x) for x in sums])
    assert totals[3] == n_val  # every real sample counted exactly once
    np.testing.assert_allclose(totals[0] / n_val, ce_ref, rtol=1e-5)
    np.testing.assert_allclose(100 * totals[1] / n_val, acc1_ref, rtol=1e-6)
    np.testing.assert_allclose(100 * totals[2] / n_val, acc5_ref, rtol=1e-6)


def test_runner_exact_eval_smoke(tmp_path):
    """validation.exact drives through the full Runner on a ragged synthetic
    val set (250 % 16 != 0, so the loader wrap-pads the final batch) — the
    exact path must execute end to end and log finite metrics."""

    class _FakeTB:
        def __init__(self):
            self.scalars = []

        def add_scalar(self, tag, value, step):
            self.scalars.append((tag, value, step))

    cfg = _tiny_cfg(tmp_path)
    cfg["dataset"]["n_samples"] = 250
    cfg["validation"]["exact"] = True
    cfg["training"]["train_iters"] = 3
    cfg["training"]["val_interval"] = 3
    tb = _FakeTB()
    runner = Runner(
        num_nodes=1,
        rank=0,
        seed=7,
        dist_url="tcp://127.0.0.1:9902",
        dist_backend="tpu",
        multiprocessing=True,
        logger_queue=None,
        global_cfg=cfg,
        tb_writer_constructor=lambda: tb,
    )
    runner()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert accs and all(np.isfinite(v) and 0.0 <= v <= 100.0 for v in accs)
