"""Multi-process log funnel (logger/MultiProcessLoggerListener).

The listener is the rank-0 side of the logging design every other
subsystem leans on (telemetry LogSink, worker pools, elastic respawn):
children put LogRecords on a multiprocessing queue via ``QueueHandler``
and a ``QueueListener`` thread drains them into the real handlers.

Kept import-light on purpose: the spawn start method re-imports this
module in the child, so nothing heavy (no jax) at module level.
"""
import logging
import logging.handlers
import multiprocessing

import pytest

from pytorch_distributed_training_tpu.logger import MultiProcessLoggerListener


class ListHandler(logging.Handler):
    """Sink handler capturing records in-process for assertions."""

    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def _make_listener():
    sink = ListHandler()

    def constructor():
        logger = logging.getLogger("test-mp-funnel")
        logger.setLevel(logging.INFO)
        logger.handlers = [sink]
        logger.propagate = False
        return logger

    return MultiProcessLoggerListener(constructor, "spawn"), sink


def _child_log(queue, messages):
    """Module-level so the spawn child can unpickle it by qualified name."""
    logger = logging.getLogger("mp-child")
    logger.setLevel(logging.INFO)
    logger.handlers = [logging.handlers.QueueHandler(queue)]
    logger.propagate = False
    for msg in messages:
        logger.info(msg)


def test_child_process_records_reach_sink_handlers():
    listener, sink = _make_listener()
    try:
        ctx = multiprocessing.get_context("spawn")
        msgs = [f"child record {i}" for i in range(5)]
        p = ctx.Process(target=_child_log, args=(listener.queue, msgs))
        p.start()
        p.join(timeout=60)
        assert p.exitcode == 0
    finally:
        listener.stop()  # stop() drains the queue before closing it
    got = [r.getMessage() for r in sink.records]
    assert got == msgs  # all records, original order, none dropped


def test_stop_drains_pending_records():
    listener, sink = _make_listener()
    qh = logging.handlers.QueueHandler(listener.queue)
    producer = logging.getLogger("test-mp-producer")
    producer.setLevel(logging.INFO)
    producer.handlers = [qh]
    producer.propagate = False
    n = 200
    for i in range(n):
        producer.info("pending %d", i)
    # no sleep/poll: stop() itself must flush whatever is still queued
    listener.stop()
    assert len(sink.records) == n
    assert sink.records[-1].getMessage() == f"pending {n - 1}"


def test_double_stop_is_safe():
    listener, _ = _make_listener()
    listener.stop()
    listener.stop()  # second stop: no raise, no hang on the closed queue


def test_respects_handler_level():
    listener, sink = _make_listener()
    sink.setLevel(logging.ERROR)
    qh = logging.handlers.QueueHandler(listener.queue)
    producer = logging.getLogger("test-mp-levels")
    producer.setLevel(logging.INFO)
    producer.handlers = [qh]
    producer.propagate = False
    producer.info("drop me")
    producer.error("keep me")
    listener.stop()
    assert [r.getMessage() for r in sink.records] == ["keep me"]


def test_get_logger_returns_constructed_logger():
    listener, sink = _make_listener()
    try:
        assert listener.get_logger().handlers == [sink]
    finally:
        listener.stop()
