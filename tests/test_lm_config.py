"""Long-context LM drivable from the config surface (VERDICT item #10).

"First-class" sequence parallelism must mean reachable by a user of the
reference-compatible entry points: ``model.name: TransformerLM`` + an LM
dataset + ``training.sequence_parallelism`` in the YAML, driven end to end
through the same Runner that drives ResNet (same flags, same log/TB tags).
"""
import json
import os

import numpy as np
import pytest

from pytorch_distributed_training_tpu.data import (
    SyntheticTextDataset,
    TokenFileDataset,
    get_dataset,
)
from pytorch_distributed_training_tpu.engine import Runner
from pytorch_distributed_training_tpu.models import TransformerLM, get_model


class _FakeTB:
    def __init__(self):
        self.scalars = []

    def add_scalar(self, tag, value, step):
        self.scalars.append((tag, float(value), int(step)))


# ------------------------------------------------------------- factory/data
def test_get_model_transformer_lm_kwargs():
    m = get_model(
        "TransformerLM", num_classes=64, embed_dim=32, depth=2, num_heads=4,
        max_len=128,
    )
    assert isinstance(m, TransformerLM)
    assert m.vocab_size == 64 and m.embed_dim == 32 and m.max_len == 128


def test_synthetic_text_deterministic_and_shifted():
    ds = get_dataset("synthetic_text", "/unused", "train", n_classes=64, seq_len=32)
    assert isinstance(ds, SyntheticTextDataset)
    inp1, tgt1 = ds[3]
    inp2, tgt2 = ds[3]
    np.testing.assert_array_equal(inp1, inp2)  # reproducible from index alone
    np.testing.assert_array_equal(inp1[1:], tgt1[:-1])  # host-shifted pair
    assert inp1.shape == (32,) and inp1.dtype == np.int32
    assert inp1.min() >= 0 and inp1.max() < 64
    # train/val streams are disjoint (different split salt)
    val = get_dataset("synthetic_text", "/unused", "val", n_classes=64, seq_len=32)
    assert not np.array_equal(val[3][0], inp1)


def test_synthetic_text_has_learnable_structure():
    """~90% of transitions follow the split's bigram table — next-token
    structure a short LM run can pick up."""
    ds = SyntheticTextDataset(n_samples=8, vocab_size=64, seq_len=256, split="train")
    hits = total = 0
    for i in range(8):
        inp, tgt = ds[i]
        for t in range(len(inp)):
            hits += tgt[t] in ds._successors[inp[t]]
            total += 1
    assert hits / total > 0.8


def test_token_file_dataset(tmp_path):
    vocab = 100
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, vocab, 1000, dtype=np.uint16)
    corpus.tofile(tmp_path / "train.bin")
    (tmp_path / "meta.json").write_text(
        json.dumps({"dtype": "uint16", "vocab_size": vocab})
    )
    ds = get_dataset("tokens", str(tmp_path), "train", n_classes=128, seq_len=64)
    assert isinstance(ds, TokenFileDataset)
    assert len(ds) == (1000 - 1) // 64
    inp, tgt = ds[2]
    np.testing.assert_array_equal(inp, corpus[128:192].astype(np.int32))
    np.testing.assert_array_equal(tgt, corpus[129:193].astype(np.int32))
    # meta vocab larger than configured n_classes is a hard error
    with pytest.raises(ValueError):
        get_dataset("tokens", str(tmp_path), "train", n_classes=50, seq_len=64)
    with pytest.raises(FileNotFoundError):
        get_dataset("tokens", str(tmp_path), "val", n_classes=128, seq_len=64)


# --------------------------------------------------------- Runner end-to-end
def _lm_cfg(seq_par: int, dataset: dict) -> dict:
    return {
        "dataset": dataset,
        "training": {
            "optimizer": {
                "name": "SGD",
                "lr": 0.1,
                "weight_decay": 1.0e-4,
                "momentum": 0.9,
            },
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": 6,
            "print_interval": 2,
            "val_interval": 3,
            "batch_size": 8,
            "num_workers": 2,
            "sync_bn": False,
            "sequence_parallelism": seq_par,
        },
        "validation": {"batch_size": 8, "num_workers": 2},
        "model": {
            "name": "TransformerLM",
            "embed_dim": 32,
            "depth": 2,
            "num_heads": 4,
        },
    }


def _run(cfg):
    tb = _FakeTB()
    runner = Runner(
        num_nodes=1,
        rank=0,
        seed=1029,
        dist_url="tcp://127.0.0.1:9941",
        dist_backend="tpu",
        multiprocessing=False,
        logger_queue=None,
        global_cfg=cfg,
        tb_writer_constructor=lambda: tb,
    )
    runner()
    return runner, tb


def test_runner_lm_ring_sp_end_to_end():
    """synthetic_text + sequence_parallelism: 4 on the 8-device mesh
    (DPx2 x SPx4 ring attention), through the reference Runner flow."""
    cfg = _lm_cfg(
        4,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.seq_par == 4
    assert runner.mesh.shape == {"data": 2, "sequence": 4}
    assert runner.iter == 6
    tags = {t for t, _, _ in tb.scalars}
    # the reference's exact five tag families drive the LM task too
    assert {"loss/train", "lr_group/0", "eval/Acc@1", "eval/Acc@5", "eval/loss"} <= tags
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert all(0.0 <= a <= 100.0 for a in accs)


def test_runner_lm_token_file_dp_end_to_end(tmp_path):
    """tokens (memory-mapped corpus) + plain DP (sequence_parallelism: 1)."""
    vocab = 64
    rng = np.random.default_rng(1)
    for split, n in (("train", 4000), ("val", 600)):
        rng.integers(0, vocab, n, dtype=np.uint16).tofile(tmp_path / f"{split}.bin")
    (tmp_path / "meta.json").write_text(json.dumps({"dtype": "uint16"}))
    cfg = _lm_cfg(
        1,
        {"name": "tokens", "root": str(tmp_path), "n_classes": vocab, "seq_len": 32},
    )
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.mesh.shape == {"data": 8, "sequence": 1}
    assert runner.iter == 6
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()


def test_runner_lm_tensor_parallel_adamw_end_to_end():
    """tensor_parallelism: 4 from the config (DPx2 x TPx4, GSPMD Megatron
    sharding) with the AdamW optimizer — also exercises the generalized
    tp_state_shardings over AdamW's mu/nu moment trees."""
    cfg = _lm_cfg(
        1,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["sequence_parallelism"] = 1
    cfg["training"]["tensor_parallelism"] = 4
    cfg["training"]["optimizer"] = {
        "name": "AdamW",
        "lr": 1.0e-3,
        "weight_decay": 1.0e-2,
    }
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.tensor_par == 4
    assert runner.mesh.shape == {"data": 2, "sequence": 1, "model": 4}
    assert runner.iter == 6
    # params actually live sharded over the model axis
    import jax as _jax

    sharded = [
        leaf
        for leaf in _jax.tree.leaves(runner.state.params)
        if not leaf.sharding.is_fully_replicated
    ]
    assert sharded, "TP run must have model-axis-sharded params"
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert accs and all(0.0 <= a <= 100.0 for a in accs)


def test_lm_parallelism_validation():
    base = {
        "name": "synthetic_text",
        "root": "/unused",
        "n_classes": 64,
        "seq_len": 30,  # NOT divisible by 4
        "n_samples": 96,
    }
    cfg = _lm_cfg(4, dict(base))
    with pytest.raises(ValueError, match="seq_len"):
        _run(cfg)
    cfg = _lm_cfg(3, dict(base))  # 3 does not divide 8 local devices
    with pytest.raises(ValueError, match="divide"):
        _run(cfg)
    cfg = _lm_cfg(1, dict(base, seq_len=32))
    cfg["training"]["tensor_parallelism"] = 8  # heads=4 < tp=8
    with pytest.raises(ValueError, match="num_heads"):
        _run(cfg)


def test_remat_matches_no_remat():
    """model.remat: true changes memory behavior, not math — identical
    logits and gradients for identical params."""
    import jax
    import jax.numpy as jnp

    tokens = np.asarray(
        np.random.default_rng(0).integers(0, 32, (2, 16)), np.int32
    )
    base = TransformerLM(vocab_size=32, max_len=16, embed_dim=16, depth=2, num_heads=2)
    rem = base.copy(remat=True)
    params = base.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss(m, p):
        return jnp.mean(m.apply({"params": p}, tokens) ** 2)

    l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(rem, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_sequence_parallelism_requires_lm(tmp_path):
    cfg = _lm_cfg(
        2,
        {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 8,
            "image_size": 32,
            "n_samples": 64,
        },
    )
    cfg["model"] = {"name": "ResNet18"}
    with pytest.raises(ValueError, match="sequence_parallelism"):
        _run(cfg)


def test_runner_lm_checkpoint_resume(tmp_path):
    """Checkpoint/resume covers the LM task too: AdamW moment trees +
    token-stream fast-forward restore through the Runner."""
    cfg = _lm_cfg(
        1,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["optimizer"] = {"name": "AdamW", "lr": 1.0e-3, "weight_decay": 0.01}
    cfg["training"]["train_iters"] = 4
    cfg["training"]["checkpoint"] = {"dir": str(tmp_path / "ck"), "interval": 2}
    runner, _ = _run(cfg)
    assert runner.iter == 4
    first_digest = np.concatenate(
        [np.asarray(x).ravel() for x in __import__("jax").tree.leaves(runner.state.params)]
    )

    # resume: a fresh Runner restores the final checkpoint and has nothing
    # left to train (iter == train_iters), state byte-identical
    runner2, _ = _run(cfg)
    assert runner2.iter == 4
    assert int(runner2.state.step) == 4
    second_digest = np.concatenate(
        [np.asarray(x).ravel() for x in __import__("jax").tree.leaves(runner2.state.params)]
    )
    np.testing.assert_array_equal(first_digest, second_digest)


def test_runner_lm_sp_tp_combined_end_to_end():
    """sequence_parallelism: 2 x tensor_parallelism: 2 from the config
    (DPx2 x SPx2 x TPx2 GSPMD on the 3-axis mesh) through the Runner."""
    cfg = _lm_cfg(
        2,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["tensor_parallelism"] = 2
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.seq_par == 2 and runner.tensor_par == 2
    assert runner.mesh.shape == {"data": 2, "sequence": 2, "model": 2}
    assert runner.model.seq_axis is None  # GSPMD path, not ring attention
    assert runner.iter == 6
    import jax as _jax

    sharded = [
        leaf
        for leaf in _jax.tree.leaves(runner.state.params)
        if not leaf.sharding.is_fully_replicated
    ]
    assert sharded, "combined run must have model-axis-sharded params"
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert accs and all(0.0 <= a <= 100.0 for a in accs)


def test_runner_lm_zero_end_to_end():
    """training.zero: ZeRO-1 moment sharding from the config; selects the
    GSPMD path even at tensor_parallelism 1 (data axis 8)."""
    cfg = _lm_cfg(
        1,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["zero"] = True
    cfg["training"]["optimizer"] = {"name": "AdamW", "lr": 1.0e-3, "weight_decay": 0.01}
    runner, tb = _run(cfg)
    assert runner.zero
    assert runner.mesh.shape == {"data": 8, "sequence": 1, "model": 1}
    import jax as _jax

    from conftest import uses_mesh_axis

    assert any(
        uses_mesh_axis(leaf.sharding, "data")
        for leaf in _jax.tree.leaves(runner.state.opt_state.mu)
    )
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()


def test_runner_lm_zero_with_sequence_parallelism():
    """zero + sequence_parallelism routes the GSPMD path (seq_axis=None),
    not ring attention — the combination must compile and run."""
    cfg = _lm_cfg(
        2,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["zero"] = True
    runner, tb = _run(cfg)
    assert runner.zero and runner.seq_par == 2
    assert runner.model.seq_axis is None  # GSPMD, not shard_map ring
    assert runner.mesh.shape == {"data": 4, "sequence": 2, "model": 1}
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()


def test_runner_image_grad_accumulation_end_to_end(tmp_path):
    """training.grad_accumulation through the Runner on the image path
    (regression: the config guard must not touch unset LM-only state)."""
    cfg = {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 8,
            "image_size": 32,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {"name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4, "momentum": 0.9},
            "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
            "train_iters": 3,
            "print_interval": 1,
            "val_interval": 2,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": True,
            "grad_accumulation": 2,
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ResNet18"},
    }
    tb = _FakeTB()
    runner = Runner(
        num_nodes=1, rank=0, seed=1029, dist_url="tcp://127.0.0.1:9942",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: tb,
    )
    runner()
    assert runner.iter == 3 and runner.grad_accum == 2
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert losses and np.isfinite(losses).all()


def test_runner_lm_pipeline_parallel_end_to_end():
    """pipeline_parallelism: 4 from the config (DPx2 x PPx4 GPipe schedule,
    parallel/pipeline.py) — stage-sharded stacked block params, microbatch
    streaming, the reference TB tag set, and finite loss end to end."""
    cfg = _lm_cfg(
        1,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["sequence_parallelism"] = 1
    cfg["training"]["pipeline_parallelism"] = 4
    cfg["model"]["depth"] = 4  # must divide by the stage count
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.pipe_par == 4 and runner.microbatches == 4
    assert runner.mesh.shape == {"data": 2, "stage": 4}
    assert runner.iter == 6
    # block params live stacked [depth, ...] and sharded over the stage axis
    import jax as _jax

    blk = _jax.tree.leaves(runner.state.params["blocks"])[0]
    assert blk.shape[0] == 4
    assert blk.sharding.spec[0] == "stage"
    tags = {t for t, _, _ in tb.scalars}
    assert {"loss/train", "lr_group/0", "eval/Acc@1", "eval/Acc@5", "eval/loss"} <= tags
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert np.isfinite(losses).all()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert accs and all(0.0 <= a <= 100.0 for a in accs)


def test_pipeline_parallelism_validation():
    base = {
        "name": "synthetic_text",
        "root": "/unused",
        "n_classes": 64,
        "seq_len": 32,
        "n_samples": 96,
    }
    # depth 2 not divisible by 4 stages
    cfg = _lm_cfg(1, dict(base))
    cfg["training"]["pipeline_parallelism"] = 4
    with pytest.raises(ValueError, match="depth"):
        _run(cfg)
    # PP x SP and PP x TP compose (round 3) but the three-way does not —
    # the pipeline mesh carries ONE inner axis besides stage
    cfg = _lm_cfg(2, dict(base))
    cfg["training"]["pipeline_parallelism"] = 2
    cfg["training"]["tensor_parallelism"] = 2
    with pytest.raises(ValueError, match="three-way"):
        _run(cfg)
    # microbatches below the stage count would deadlock the schedule
    cfg = _lm_cfg(1, dict(base))
    cfg["training"]["pipeline_parallelism"] = 4
    cfg["training"]["microbatches"] = 2
    with pytest.raises(ValueError, match="microbatches"):
        _run(cfg)
    # LARS trust ratios don't survive the stacked-layer layout
    cfg = _lm_cfg(1, dict(base))
    cfg["training"]["pipeline_parallelism"] = 4
    cfg["model"]["depth"] = 4
    cfg["training"]["optimizer"] = {"name": "LARS", "lr": 0.1, "momentum": 0.9}
    with pytest.raises(ValueError, match="LARS"):
        _run(cfg)


def test_runner_lm_moe_expert_parallel_end_to_end():
    """model.moe_experts from the config: MoE routes to the GSPMD path,
    expert weights shard over the model axis (expert parallelism), and the
    aux load-balancing loss trains end to end with finite values."""
    cfg = _lm_cfg(
        1,
        {
            "name": "synthetic_text",
            "root": "/unused",
            "n_classes": 64,
            "seq_len": 32,
            "n_samples": 96,
        },
    )
    cfg["training"]["sequence_parallelism"] = 1
    cfg["training"]["tensor_parallelism"] = 4
    cfg["model"]["moe_experts"] = 4
    cfg["model"]["moe_top_k"] = 2
    runner, tb = _run(cfg)
    assert runner.is_lm and runner.is_moe and runner.tensor_par == 4
    assert runner.mesh.shape == {"data": 2, "sequence": 1, "model": 4}
    assert runner.iter == 6
    import jax as _jax

    wi = runner.state.params["block1"]["moe"]["wi"]
    assert wi.sharding.spec[0] == "model"
    losses = [v for t, v, _ in tb.scalars if t == "loss/train"]
    assert losses and np.isfinite(losses).all()
    accs = [v for t, v, _ in tb.scalars if t == "eval/Acc@1"]
    assert accs and all(0.0 <= a <= 100.0 for a in accs)


def test_moe_validation():
    base = {
        "name": "synthetic_text",
        "root": "/unused",
        "n_classes": 64,
        "seq_len": 32,
        "n_samples": 96,
    }
    # experts must split evenly over the model axis
    cfg = _lm_cfg(1, dict(base))
    cfg["training"]["tensor_parallelism"] = 4
    cfg["model"]["moe_experts"] = 6
    with pytest.raises(ValueError, match="moe_experts"):
        _run(cfg)
    # MoE does not compose with pipeline parallelism
    cfg = _lm_cfg(1, dict(base))
    cfg["training"]["pipeline_parallelism"] = 4
    cfg["model"]["depth"] = 4
    cfg["model"]["moe_experts"] = 4
    with pytest.raises(ValueError, match="moe"):
        _run(cfg)
    # moe_every outside [1, depth] is a config error, not a silent no-op
    cfg = _lm_cfg(1, dict(base))
    cfg["model"]["moe_experts"] = 4
    cfg["model"]["moe_every"] = 0
    with pytest.raises(ValueError, match="moe_every"):
        _run(cfg)
    cfg = _lm_cfg(1, dict(base))
    cfg["model"]["moe_experts"] = 4
    cfg["model"]["depth"] = 2
    cfg["model"]["moe_every"] = 3
    with pytest.raises(ValueError, match="moe_every"):
        _run(cfg)


def test_remat_policy_matches_nothing_policy():
    """model.remat_policy: "dots" changes WHAT is saved, never the math —
    losses equal the default policy (and bad values raise)."""
    import numpy as np

    from pytorch_distributed_training_tpu.engine import (
        TrainState,
        build_lm_train_step,
    )
    from pytorch_distributed_training_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from pytorch_distributed_training_tpu.optimizers import AdamW
    from pytorch_distributed_training_tpu.parallel import (
        make_sp_mesh,
        replicated_sharding,
    )
    from pytorch_distributed_training_tpu.schedulers import cosine_lr

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    toks = rng.integers(0, 64, (8, 33)).astype(np.int32)

    def run(policy):
        lm = TransformerLM(
            vocab_size=64, max_len=32, embed_dim=32, depth=2, num_heads=4,
            remat=True, remat_policy=policy,
        )
        mesh = make_sp_mesh(1)
        params = lm.init(jax.random.PRNGKey(0), jnp.asarray(toks[:1, :32]))[
            "params"
        ]
        opt = AdamW(lr=1e-3, weight_decay=0.01)
        state = TrainState(
            params=params, batch_stats={}, opt_state=opt.init(params)
        )
        state = jax.device_put(state, replicated_sharding(mesh))
        step = build_lm_train_step(lm, opt, cosine_lr(1e-3, 100), mesh)
        losses = []
        for _ in range(2):
            state, loss = step(
                state, jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
            )
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run("nothing"), run("dots"), rtol=1e-6)

    with pytest.raises(ValueError, match="remat_policy must be"):
        run("everything")
