"""Bucketed, backward-overlapped gradient reduction (engine/comm.py).

Parity strategy (and why each comparison is trustworthy on this image):

- The overlap path differentiates the LOCAL loss — the backward carries no
  collective — so its AD is plain per-device autodiff, exact under every
  shard_map implementation.  The reduction then happens as FORWARD-only
  collectives, which the pre-vma experimental shard_map executes correctly.
  8-device overlap/zero1 runs are therefore compared against an UNSHARDED
  plain-jax reference.
- The legacy (implicit) path differentiates through an in-body collective,
  whose pre-vma AD transpose is wrong on multi-device meshes (see
  utils/jax_compat.py) — baseline-vs-overlap comparisons are therefore
  restricted to 1-device meshes, where collectives are identity and both
  paths are exact (and the parity is BITWISE).

The ``shard_map_compat`` fixture self-provisions ``jax.shard_map`` per test
and removes the graft on teardown, so this file passes on the vanilla CPU
image without changing any other test file's environment.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_training_tpu.engine.comm import (
    Bucket,
    CommConfig,
    plan_buckets,
    reduce_gradients,
    zero1_init,
    zero1_slot_count,
)
from pytorch_distributed_training_tpu.utils import jax_compat

DATA = "data"
SEQ_AXIS = "sequence"


@pytest.fixture()
def shard_map_compat(monkeypatch):
    """Graft ``jax.shard_map`` for one test, restore the world after.

    Scoped per-test (not module/session) so alphabetically-later test files
    keep seeing the unmodified jax module — the tier-1 failure set of the
    shard_map-dependent suites must not change underneath them.
    """
    if hasattr(jax, "shard_map"):  # real toolchain graft: nothing to do
        yield
        return
    monkeypatch.setenv("PDT_JAX_COMPAT", "1")
    jax_compat.install()
    assert hasattr(jax, "shard_map")
    try:
        yield
    finally:
        delattr(jax, "shard_map")


# --------------------------------------------------------------------- #
# Bucket planner (pure host-side: no devices, no fixture)
# --------------------------------------------------------------------- #


def _leaves(*specs):
    return [jnp.zeros(shape, dtype) for shape, dtype in specs]


def test_plan_reverse_order_and_cap():
    # 4 leaves of 64 f32 (256 B) with a 512 B cap -> two buckets of two,
    # walked back-to-front
    leaves = _leaves(*[((64,), jnp.float32)] * 4)
    plan = plan_buckets(leaves, 512 / 2**20)
    assert [b.indices for b in plan] == [(3, 2), (1, 0)]
    assert all(b.size == 128 and b.dtype == jnp.float32 for b in plan)


def test_plan_dtype_change_closes_bucket():
    leaves = _leaves(
        ((8,), jnp.float32), ((8,), jnp.bfloat16), ((8,), jnp.bfloat16)
    )
    plan = plan_buckets(leaves, 1.0)
    assert [(b.indices, b.dtype) for b in plan] == [
        ((2, 1), jnp.dtype(jnp.bfloat16)),
        ((0,), jnp.dtype(jnp.float32)),
    ]


def test_plan_oversized_leaf_becomes_singleton():
    # middle leaf alone exceeds the cap: it must get its own bucket without
    # dragging neighbors in, and the walk stays strictly reverse-ordered
    leaves = _leaves(((4,), jnp.float32), ((10_000,), jnp.float32), ((4,), jnp.float32))
    plan = plan_buckets(leaves, 64 / 2**20)
    assert [b.indices for b in plan] == [(2,), (1,), (0,)]
    assert plan[1].size == 10_000


def test_plan_empty_tree():
    assert plan_buckets([], 25.0) == []


def test_plan_accepts_shape_structs():
    # init-time planning runs on ShapeDtypeStruct, not concrete arrays
    structs = [
        jax.ShapeDtypeStruct((16, 4), jnp.float32),
        jax.ShapeDtypeStruct((3,), jnp.float32),
    ]
    plan = plan_buckets(structs, 25.0)
    assert plan == [Bucket((1, 0), jnp.dtype(jnp.float32), 67)]


def test_reduce_gradients_validates_op_and_passes_empty():
    with pytest.raises(ValueError, match="psum or pmean"):
        reduce_gradients({"g": jnp.ones(3)}, CommConfig(overlap=True), DATA, op="pmax")
    empty = {}
    assert reduce_gradients(empty, CommConfig(overlap=True), DATA) is empty


# --------------------------------------------------------------------- #
# training.comm config parsing (engine/topology.parse_comm)
# --------------------------------------------------------------------- #


class _R:
    pass


def _parse(train_cfg):
    from pytorch_distributed_training_tpu.engine.topology import parse_comm

    r = _R()
    parse_comm(r, train_cfg)
    return r.comm


def test_parse_comm_default_off():
    assert _parse({}) == CommConfig(overlap=False, bucket_mb=25.0, reduce_dtype=None)
    assert _parse({"comm": {}}).overlap is False


def test_parse_comm_full_block():
    cfg = _parse({"comm": {"overlap": True, "bucket_mb": 4, "reduce_dtype": "bfloat16"}})
    assert cfg == CommConfig(overlap=True, bucket_mb=4.0, reduce_dtype="bfloat16")


def test_parse_comm_rejects_bad_keys_and_values():
    with pytest.raises(ValueError, match="unknown key"):
        _parse({"comm": {"overlap": True, "bucket_size_mb": 4}})
    with pytest.raises(ValueError, match="bucket_mb"):
        _parse({"comm": {"bucket_mb": 0}})
    with pytest.raises(ValueError, match="reduce_dtype"):
        _parse({"comm": {"reduce_dtype": "float16"}})


# --------------------------------------------------------------------- #
# zero1 builder validation (raises before any shard_map is traced)
# --------------------------------------------------------------------- #


def test_zero1_validation_errors():
    from pytorch_distributed_training_tpu.engine.sp_steps import build_lm_train_step
    from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
    from pytorch_distributed_training_tpu.optimizers import LAMB, LARS, SGD, AdamW
    from pytorch_distributed_training_tpu.parallel import make_sp_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    lm = TransformerLM(vocab_size=32, max_len=16, embed_dim=16, depth=1, num_heads=2)
    opt = SGD(lr=0.1, momentum=0.9)
    lr_fn = multi_step_lr(0.1, [], 0.1)
    on = CommConfig(overlap=True)

    with pytest.raises(ValueError, match="comm.overlap"):
        build_lm_train_step(lm, opt, lr_fn, make_sp_mesh(1), zero1=True)
    with pytest.raises(ValueError, match="anomaly"):
        build_lm_train_step(
            lm, opt, lr_fn, make_sp_mesh(1), comm=on, zero1=True, anomaly_factor=10.0
        )
    with pytest.raises(ValueError, match="sequence_parallelism"):
        build_lm_train_step(lm, opt, lr_fn, make_sp_mesh(4), comm=on, zero1=True)

    # the optimizer gate: elementwise kernels only
    assert zero1_slot_count(SGD(lr=0.1)) == 1
    assert zero1_slot_count(AdamW(lr=1e-3)) == 2
    with pytest.raises(ValueError, match="LARS/LAMB"):
        zero1_slot_count(LARS(lr=0.1))
    with pytest.raises(ValueError, match="LARS/LAMB"):
        zero1_slot_count(LAMB(lr=1e-3))
    with pytest.raises(ValueError, match="exclude_norm_bias"):
        zero1_slot_count(AdamW(lr=1e-3, exclude_norm_bias=True))


# --------------------------------------------------------------------- #
# Forward-only reduction: bucketed == monolithic, bitwise (8 devices)
# --------------------------------------------------------------------- #


def _grad_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.standard_normal((8, 32)).astype(np.float32)),
        "w2": jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32)),
        "b": jnp.asarray(rng.standard_normal((8,)).astype(np.float32)),
        "h": jnp.asarray(
            rng.standard_normal((8, 8)).astype(np.float32)
        ).astype(jnp.bfloat16),
    }


def _run_reduce(tree, cfg, op):
    mesh = Mesh(np.array(jax.devices()), (DATA,))

    def body(t):
        red = reduce_gradients(t, cfg, DATA, op=op)
        mono = jax.tree.map(
            lambda x: jax.lax.psum(x, DATA) if op == "psum" else jax.lax.pmean(x, DATA),
            t,
        )
        return red, mono

    return jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(P(DATA),), out_specs=P())
    )(tree)


@pytest.mark.parametrize("op", ["psum", "pmean"])
@pytest.mark.parametrize("bucket_mb", [25.0, 64 / 2**20])
def test_bucketed_reduce_matches_monolithic_bitwise(shard_map_compat, op, bucket_mb):
    """Concatenation commutes with elementwise reduction: whatever the
    bucketing (one giant bucket or a long barrier chain of tiny ones), the
    reduced tree must equal the per-leaf collective BITWISE."""
    tree = _grad_tree()
    red, mono = _run_reduce(tree, CommConfig(overlap=True, bucket_mb=bucket_mb), op)
    for a, b in zip(jax.tree.leaves(red), jax.tree.leaves(mono)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reduce_dtype_cast_roundtrip(shard_map_compat):
    """reduce_dtype=bfloat16: the collective runs in bf16 but every output
    leaf comes back in its own dtype, close to the f32 reduction."""
    tree = _grad_tree(seed=1)
    red, mono = _run_reduce(
        tree, CommConfig(overlap=True, bucket_mb=25.0, reduce_dtype="bfloat16"), "pmean"
    )
    for (k, a), b in zip(sorted(red.items()), [v for _, v in sorted(mono.items())]):
        assert a.dtype == tree[k].dtype
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-2, atol=2e-2
        )


def test_bucket_bytes_histogram_recorded(shard_map_compat):
    from pytorch_distributed_training_tpu.telemetry import get_registry, reset_registry

    reset_registry()
    try:
        _run_reduce(_grad_tree(), CommConfig(overlap=True, bucket_mb=64 / 2**20), "psum")
        snap = get_registry().histogram("comm_bucket_bytes").snapshot()
        assert snap["count"] >= 2  # tiny cap -> several buckets observed
        assert snap["max"] > 0
    finally:
        reset_registry()


# --------------------------------------------------------------------- #
# DP image path (engine/steps.py)
# --------------------------------------------------------------------- #

_N_CLASSES = 4


def _tiny_cnn():
    import flax.linen as nn

    class _TinyNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(4, (3, 3))(x)
            x = nn.relu(x)
            x = x.mean(axis=(1, 2))
            return nn.Dense(_N_CLASSES)(x)

    return _TinyNet()


def _dp_fixtures(batch=16, seed=5):
    from pytorch_distributed_training_tpu.engine import init_train_state
    from pytorch_distributed_training_tpu.optimizers import SGD

    rng = np.random.default_rng(seed)
    img = jnp.asarray(rng.standard_normal((batch, 8, 8, 3)).astype(np.float32))
    label = jnp.asarray(rng.integers(0, _N_CLASSES, (batch,)).astype(np.int32))
    model = _tiny_cnn()
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    state = init_train_state(model, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    return model, opt, state, img, label


def test_dp_overlap_bitwise_on_single_device(shard_map_compat):
    """1-device mesh: collectives are identity in both paths, so the
    bucketed explicit reduction must reproduce the legacy step BITWISE."""
    from pytorch_distributed_training_tpu.engine import build_train_step
    from pytorch_distributed_training_tpu.parallel import make_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    model, opt, state, img, label = _dp_fixtures()
    lr_fn = multi_step_lr(0.05, [], 0.1)
    mesh1 = make_mesh(devices=jax.devices()[:1])
    base = build_train_step(model, opt, lr_fn, mesh1, sync_bn=False, donate=False)
    over = build_train_step(
        model, opt, lr_fn, mesh1, sync_bn=False, donate=False,
        comm=CommConfig(overlap=True, bucket_mb=1e-4),
    )
    s_base, loss_base = base(state, img, label)
    s_over, loss_over = over(state, img, label)
    assert float(loss_base) == float(loss_over)
    for a, b in zip(jax.tree.leaves(s_base.params), jax.tree.leaves(s_over.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_overlap_8dev_matches_unsharded(shard_map_compat):
    """8-device overlap step == plain-jax full-batch step.  The overlap
    backward is collective-free (exact local AD) and pmean(g_local) over a
    power-of-two mesh is the full-batch mean up to reassociation."""
    from pytorch_distributed_training_tpu.engine import build_train_step
    from pytorch_distributed_training_tpu.ops import cross_entropy_loss
    from pytorch_distributed_training_tpu.parallel import batch_sharding, make_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    model, opt, state, img, label = _dp_fixtures()
    lr_fn = multi_step_lr(0.05, [], 0.1)

    def ref_loss(p):
        return cross_entropy_loss(model.apply({"params": p}, img, train=False), label)

    _, grads = jax.value_and_grad(ref_loss)(state.params)
    ref_params, _ = opt.update(grads, opt.init(state.params), state.params, 0.05)

    mesh = make_mesh()
    step = build_train_step(
        model, opt, lr_fn, mesh, sync_bn=False, donate=False,
        comm=CommConfig(overlap=True, bucket_mb=1e-4),
    )
    s8, _ = step(
        state,
        jax.device_put(img, batch_sharding(mesh, 4)),
        jax.device_put(label, batch_sharding(mesh, 1)),
    )
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-6)


# --------------------------------------------------------------------- #
# SP LM path (engine/sp_steps.py) + ZeRO-1 + grad accumulation
# --------------------------------------------------------------------- #

VOCAB, SEQ, BATCH = 32, 16, 16


def _lm_fixtures(seed=2):
    from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
    from pytorch_distributed_training_tpu.optimizers import SGD

    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, (BATCH, SEQ + 1)).astype(np.int32)
    tokens, labels = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    mk = lambda ax: TransformerLM(  # noqa: E731
        vocab_size=VOCAB, max_len=SEQ, embed_dim=16, depth=1, num_heads=2,
        seq_axis=ax,
    )
    params = mk(None).init(jax.random.PRNGKey(0), tokens)["params"]
    return mk, params, SGD(lr=0.05, momentum=0.9, weight_decay=1e-4), tokens, labels


def _lm_reference(mk, params, opt, tokens, labels, steps=1):
    from pytorch_distributed_training_tpu.engine.sp_steps import lm_loss_local

    ref_model = mk(None)

    def ref_loss(p):
        return lm_loss_local(ref_model.apply({"params": p}, tokens), labels, labels.size)

    opt_state = opt.init(params)
    for _ in range(steps):
        _, grads = jax.value_and_grad(ref_loss)(params)
        params, opt_state = opt.update(grads, opt_state, params, 0.05)
    return params


def test_sp_overlap_bitwise_on_single_device(shard_map_compat):
    """(1, 1) mesh: the SP objective's psum is identity, so legacy vs
    overlap must agree BITWISE at grad_accum == 1 (identical sum)."""
    from pytorch_distributed_training_tpu.engine import TrainState, build_lm_train_step
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    mk, params, opt, tokens, labels = _lm_fixtures()
    lr_fn = multi_step_lr(0.05, [], 0.1)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), (DATA, SEQ_AXIS))
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    base = build_lm_train_step(mk(SEQ_AXIS), opt, lr_fn, mesh, donate=False)
    over = build_lm_train_step(
        mk(SEQ_AXIS), opt, lr_fn, mesh, donate=False,
        comm=CommConfig(overlap=True, bucket_mb=1e-4),
    )
    s_base, loss_base = base(state, tokens, labels)
    s_over, loss_over = over(state, tokens, labels)
    assert float(loss_base) == float(loss_over)
    for a, b in zip(jax.tree.leaves(s_base.params), jax.tree.leaves(s_over.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sp_overlap_8dev_matches_unsharded(shard_map_compat):
    from pytorch_distributed_training_tpu.engine import TrainState, build_lm_train_step
    from pytorch_distributed_training_tpu.parallel import make_sp_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    mk, params, opt, tokens, labels = _lm_fixtures()
    ref_params = _lm_reference(mk, params, opt, tokens, labels)
    mesh = make_sp_mesh(1)  # data=8, sequence=1
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    step = build_lm_train_step(
        mk(SEQ_AXIS), opt, multi_step_lr(0.05, [], 0.1), mesh, donate=False,
        comm=CommConfig(overlap=True, bucket_mb=1e-4),
    )
    s2, _ = step(state, tokens, labels)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_sp_overlap_grad_accum_composition(shard_map_compat):
    """grad_accum=2 under overlap: micros accumulate locally, ONE bucketed
    reduction per step (DDP no_sync semantics) — same total, reassociated."""
    from pytorch_distributed_training_tpu.engine import TrainState, build_lm_train_step
    from pytorch_distributed_training_tpu.parallel import make_sp_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    mk, params, opt, tokens, labels = _lm_fixtures(seed=3)
    ref_params = _lm_reference(mk, params, opt, tokens, labels)
    mesh = make_sp_mesh(1)
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    step = build_lm_train_step(
        mk(SEQ_AXIS), opt, multi_step_lr(0.05, [], 0.1), mesh, donate=False,
        grad_accum=2, comm=CommConfig(overlap=True, bucket_mb=1e-4),
    )
    s2, _ = step(state, tokens, labels)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_zero1_8dev_matches_unsharded(shard_map_compat):
    """Two ZeRO-1 steps (reduce-scatter + sharded update + all-gather) ==
    two plain full-batch steps.  Two steps exercise the momentum buffers
    living as flat 1/n shards, including SGD's first-step buffer init, and
    the tiny bucket_mb forces multi-bucket padding (size % 8 != 0)."""
    from pytorch_distributed_training_tpu.engine import TrainState, build_lm_train_step
    from pytorch_distributed_training_tpu.parallel import make_sp_mesh
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    mk, params, opt, tokens, labels = _lm_fixtures(seed=4)
    ref_params = _lm_reference(mk, params, opt, tokens, labels, steps=2)
    cfg = CommConfig(overlap=True, bucket_mb=1e-3)
    mesh = make_sp_mesh(1)
    z0 = zero1_init(opt, params, cfg, 8)
    state = TrainState(params=params, batch_stats={}, opt_state=z0)
    step = build_lm_train_step(
        mk(SEQ_AXIS), opt, multi_step_lr(0.05, [], 0.1), mesh, donate=False,
        comm=cfg, zero1=True,
    )
    for _ in range(2):
        state, loss = step(state, tokens, labels)
    assert np.isfinite(float(loss))
    assert int(state.opt_state.step) == 2
    # moments really are 1/n-sharded over the data axis
    slot_leaf = state.opt_state.slots[0][0]
    assert not slot_leaf.sharding.is_fully_replicated
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)
