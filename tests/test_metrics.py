"""accuracy + AverageMeter parity (reference: train_distributed.py:305-321)."""
import pytest
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.metrics import AverageMeter, accuracy


@pytest.mark.quick
def test_accuracy_topk():
    # 4 samples, 6 classes; construct known top-1/top-5 membership.
    logits = jnp.array(
        [
            [9.0, 1, 2, 3, 4, 5],  # top1=0
            [0.0, 9, 2, 3, 4, 5],  # top1=1
            [5.0, 4, 3, 2, 1, 0],  # top1=0
            [0.0, 1, 2, 3, 4, 9],  # top1=5
        ]
    )
    labels = jnp.array([0, 1, 5, 0])  # hits: yes, yes, no(top5? 5 ranks 6th? see below), no
    acc1, acc5 = accuracy(logits, labels, topk=(1, 5))
    # top-1: samples 0,1 correct -> 50%
    assert np.isclose(float(acc1), 50.0)
    # top-5 of sample 2: classes [0,1,2,3,4] -> label 5 NOT in top-5.
    # top-5 of sample 3: classes [5,4,3,2,1] -> label 0 NOT in top-5.
    assert np.isclose(float(acc5), 50.0)


def test_accuracy_matches_torch_reference_impl():
    """Cross-check against the classic pytorch-examples accuracy()."""
    import torch

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 100)).astype(np.float32)
    labels = rng.integers(0, 100, size=(64,))

    t_logits, t_labels = torch.tensor(logits), torch.tensor(labels)
    maxk = 5
    _, pred = t_logits.topk(maxk, 1, True, True)
    correct = pred.t().eq(t_labels.view(1, -1).expand_as(pred.t()))
    ref1 = correct[:1].reshape(-1).float().sum(0) * 100.0 / 64
    ref5 = correct[:5].reshape(-1).float().sum(0) * 100.0 / 64

    acc1, acc5 = accuracy(jnp.asarray(logits), jnp.asarray(labels), topk=(1, 5))
    assert np.isclose(float(acc1), float(ref1))
    assert np.isclose(float(acc5), float(ref5))


def test_average_meter_unweighted():
    m = AverageMeter()
    assert m.value() == 0.0
    m.update(1.0)
    m.update(3.0)
    assert m.value() == 2.0  # unweighted mean over updates
    m.reset()
    m.update(5.0, n=4)
    m.update(1.0)
    assert np.isclose(m.value(), 21.0 / 5)
