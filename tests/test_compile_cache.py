"""Persistent XLA compilation cache (training.compile_cache).

The TPU-native analog of the reference's ``cudnn.benchmark = True``
(train_distributed.py:54; SURVEY.md §2.3 "cuDNN autotune" row): amortize
program compilation across launches via JAX's persistent cache.
"""
import os

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_training_tpu.utils import enable_compile_cache


@pytest.fixture
def _restore_cache_config():
    saved = {
        name: getattr(jax.config, name)
        for name in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
        )
    }
    yield
    for name, value in saved.items():
        jax.config.update(name, value)
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()  # drop the initialized cache object too
    except Exception:
        pass


def test_enable_compile_cache_writes_entries(tmp_path, _restore_cache_config):
    cache_dir = tmp_path / "xla-cache"
    returned = enable_compile_cache(str(cache_dir))
    assert returned == str(cache_dir)
    assert cache_dir.is_dir()

    # A program this process has never compiled: its executable must land in
    # the cache directory (thresholds are zeroed by enable_compile_cache, so
    # even a trivial compile is persisted).
    @jax.jit
    def f(x):
        return jnp.sin(x) * 41.25 + jnp.cos(x) ** 3

    f(jnp.arange(7.0)).block_until_ready()
    entries = list(cache_dir.iterdir())
    assert entries, "no cache entries written"


def test_runner_config_key_wires_cache(tmp_path, _restore_cache_config):
    """training.compile_cache: the Runner enables the cache before building
    its compiled steps, so a config-driven run populates the directory."""
    from pytorch_distributed_training_tpu.engine import Runner

    cache_dir = tmp_path / "run-cache"
    cfg = {
        "dataset": {
            "name": "synthetic",
            "root": str(tmp_path),
            "n_classes": 4,
            "image_size": 32,
            "n_samples": 64,
        },
        "training": {
            "optimizer": {
                "name": "SGD", "lr": 0.05, "weight_decay": 1.0e-4, "momentum": 0.9,
            },
            "lr_schedule": {"name": "multi_step", "milestones": [4], "gamma": 0.1},
            "train_iters": 2,
            "print_interval": 1,
            "val_interval": 2,
            "batch_size": 16,
            "num_workers": 2,
            "sync_bn": False,
            "compile_cache": str(cache_dir),
        },
        "validation": {"batch_size": 16, "num_workers": 2},
        "model": {"name": "ResNet18"},
    }
    runner = Runner(
        num_nodes=1,
        rank=0,
        seed=7,
        dist_url="tcp://127.0.0.1:9907",
        dist_backend="tpu",
        multiprocessing=False,
        logger_queue=None,
        global_cfg=cfg,
        tb_writer_constructor=lambda: None,
    )
    runner()
    assert runner.iter == 2
    assert cache_dir.is_dir()
    assert any(cache_dir.iterdir()), "Runner did not populate the compile cache"
    assert jax.config.jax_compilation_cache_dir == str(cache_dir)
