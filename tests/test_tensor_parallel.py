"""Tensor parallelism: GSPMD TP step vs single-device oracle.

VERDICT.md r1 #5 / ADVICE.md r1 (medium): the TP path shipped with zero
coverage.  Two properties pin it down:

  1. spec coverage — ``lm_tp_param_specs`` must hit every Megatron-shardable
     param of a REAL ``TransformerLM`` tree (qkv/fc1 column, proj/fc2 row),
     and nothing else;
  2. numerics — one DP(2) x TP(4) step on the 8-fake-device mesh must equal
     the single-device step on the full batch (loss AND updated params),
     which only holds if the partitioner's collectives (partial-sum
     all-reduce after row-parallel matmuls, gradient all-reduce over data)
     are all inserted correctly.
"""
import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_tpu.engine import TrainState
from pytorch_distributed_training_tpu.engine.tp_steps import build_tp_lm_train_step
from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_training_tpu.ops import cross_entropy_loss
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import make_mesh
from pytorch_distributed_training_tpu.parallel.tensor import (
    lm_tp_param_specs,
    lm_tp_shardings,
)
from pytorch_distributed_training_tpu.schedulers import multi_step_lr

VOCAB, SEQ, BATCH = 64, 16, 8


def _model():
    # embed_dim=32, heads=4: TP=4 puts one head per shard; fc1 128/4=32
    return TransformerLM(
        vocab_size=VOCAB, max_len=SEQ, embed_dim=32, depth=2, num_heads=4,
        seq_axis=None,
    )


def _data(seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, VOCAB, (BATCH, SEQ + 1)).astype(np.int32)
    return jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:])


def test_tp_specs_cover_transformer_tree():
    """_spec_for must shard every qkv/fc1 (column) and proj/fc2 (row) param
    of the real TransformerLM tree and replicate everything else."""
    model = _model()
    tokens, _ = _data()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    specs = lm_tp_param_specs(params)

    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    sharded = {p for p, s in flat.items() if s != P()}
    assert sharded, "no params sharded — _spec_for matched nothing"
    # per block: qkv kernel+bias, proj kernel, fc1 kernel+bias, fc2 kernel
    for blk in ("block0", "block1"):
        assert flat[f"{blk}/attn/qkv/kernel"] == P(None, "model")
        assert flat[f"{blk}/attn/qkv/bias"] == P("model")
        assert flat[f"{blk}/attn/proj/kernel"] == P("model", None)
        assert flat[f"{blk}/mlp/fc1/kernel"] == P(None, "model")
        assert flat[f"{blk}/mlp/fc1/bias"] == P("model")
        assert flat[f"{blk}/mlp/fc2/kernel"] == P("model", None)
    expected = {
        f"{blk}/{name}"
        for blk in ("block0", "block1")
        for name in (
            "attn/qkv/kernel", "attn/qkv/bias", "attn/proj/kernel",
            "mlp/fc1/kernel", "mlp/fc1/bias", "mlp/fc2/kernel",
        )
    }
    assert sharded == expected, sharded ^ expected
    # embeddings / layernorms / head / proj+fc2 biases stay replicated
    for p in ("tok_embedding", "pos_embedding", "ln/scale", "head/kernel",
              "block0/attn/proj/bias", "block0/mlp/fc2/bias"):
        assert flat[p] == P(), p


@pytest.mark.quick
def test_tp_step_matches_single_device():
    tokens, labels = _data(seed=1)
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    model = _model()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    # ---- single-device reference ------------------------------------------
    def ref_loss(p):
        logits = model.apply({"params": p}, tokens)
        return cross_entropy_loss(
            logits.reshape(-1, VOCAB), labels.reshape(-1)
        )

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    params_ref, _ = opt.update(grads_ref, opt.init(params), params, 0.05)

    # ---- DP(2) x TP(4) GSPMD step -----------------------------------------
    from pytorch_distributed_training_tpu.parallel.tensor import tp_state_shardings

    mesh = make_mesh(model_parallelism=4)
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    # place the state in its TP layout before the first call
    state = jax.device_put(state, tp_state_shardings(state, mesh))
    step = build_tp_lm_train_step(model, opt, lr_fn, mesh, donate=False)(state)
    state2, loss_tp = step(state, tokens, labels)

    assert np.isclose(float(loss_tp), float(loss_ref), atol=1e-5), (loss_tp, loss_ref)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_ref),
        jax.tree_util.tree_leaves(state2.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_tp_shardings_match_specs():
    """lm_tp_shardings mirrors lm_tp_param_specs with NamedShardings."""
    model = _model()
    tokens, _ = _data()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mesh = make_mesh(model_parallelism=4)
    shardings = lm_tp_shardings(params, mesh)
    specs = lm_tp_param_specs(params)
    for sh, sp in zip(
        jax.tree_util.tree_leaves(shardings), jax.tree_util.tree_leaves(specs)
    ):
        assert sh.spec == sp


def test_3d_dp_sp_tp_step_matches_single_device():
    """DP(2) x SP(2) x TP(2) on the 3-axis mesh: tokens shard over data AND
    sequence while params shard over model — the GSPMD partitioner must
    insert the sequence resharding around attention (Ulysses-style) plus
    the Megatron all-reduces, and the step must still equal the
    single-device full-batch step exactly."""
    from pytorch_distributed_training_tpu.parallel import make_3d_mesh
    from pytorch_distributed_training_tpu.parallel.tensor import tp_state_shardings

    tokens, labels = _data(seed=2)
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    model = _model()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def ref_loss(p):
        logits = model.apply({"params": p}, tokens)
        return cross_entropy_loss(logits.reshape(-1, VOCAB), labels.reshape(-1))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    params_ref, _ = opt.update(grads_ref, opt.init(params), params, 0.05)

    mesh = make_3d_mesh(sequence_parallelism=2, model_parallelism=2)
    assert mesh.shape == {"data": 2, "sequence": 2, "model": 2}
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, tp_state_shardings(state, mesh))
    step = build_tp_lm_train_step(model, opt, lr_fn, mesh, donate=False)(state)
    state2, loss_3d = step(state, tokens, labels)

    assert np.isclose(float(loss_3d), float(loss_ref), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_ref),
        jax.tree_util.tree_leaves(state2.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_zero1_sharded_moments_match_plain():
    """training.zero (ZeRO-1): optimizer moments sharded over the data axis
    must yield EXACTLY the same step as fully-mirrored moments, with the
    big moment leaves actually sharded."""
    from pytorch_distributed_training_tpu.optimizers import AdamW
    from pytorch_distributed_training_tpu.parallel import make_3d_mesh
    from pytorch_distributed_training_tpu.parallel.tensor import tp_state_shardings

    tokens, labels = _data(seed=3)
    opt = AdamW(lr=1e-3, weight_decay=0.01)
    lr_fn = multi_step_lr(1e-3, [], 0.1)
    model = _model()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mesh = make_3d_mesh(1, 2)  # data 4 x model 2

    def run(zero):
        state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
        state = jax.device_put(state, tp_state_shardings(state, mesh, zero=zero))
        step = build_tp_lm_train_step(model, opt, lr_fn, mesh, donate=False, zero=zero)(state)
        return step(state, tokens, labels)

    s_plain, l_plain = run(False)
    s_zero, l_zero = run(True)
    assert np.isclose(float(l_plain), float(l_zero), atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_plain.params),
        jax.tree_util.tree_leaves(s_zero.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
    from conftest import uses_mesh_axis

    mu_leaves = jax.tree_util.tree_leaves(s_zero.opt_state.mu)
    sharded_over_data = [l for l in mu_leaves if uses_mesh_axis(l.sharding, "data")]
    assert sharded_over_data, "ZeRO must shard moment leaves over the data axis"
    # with TP active, even the row-parallel (proj/fc2) KERNEL moments shard
    # over data on a free dimension; the only legitimately unsharded leaves
    # are the model-sharded 1-D biases (qkv/fc1 bias: P(model), no free dim)
    flat_mu = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(s_zero.opt_state.mu)[0]
    }
    for name in ("block0/attn/proj/kernel", "block0/mlp/fc2/kernel"):
        assert uses_mesh_axis(flat_mu[name].sharding, "data"), name
    unsharded = {n for n, l in flat_mu.items() if not uses_mesh_axis(l.sharding, "data")}
    assert unsharded <= {
        f"{b}/{n}" for b in ("block0", "block1")
        for n in ("attn/qkv/bias", "mlp/fc1/bias")
    }, unsharded


@pytest.mark.quick
@pytest.mark.slow
def test_zero2_sharded_grads_match_plain():
    """training.zero: 2 (ZeRO-2): gradient buffers constrained to the
    data-sharded layout must yield EXACTLY the plain-DP step — with and
    without grad accumulation (which exercises the sharded accumulator
    carried across micro-batches).

    SGD+momentum, not AdamW: the scatter legitimately changes the f32
    gradient-summation ORDER, and AdamW's ~sign(g) normalization amplifies
    that rounding to O(lr) on near-zero grads — SGD keeps reduction-order
    noise at rounding scale, so the comparison stays tight."""
    from pytorch_distributed_training_tpu.parallel import make_3d_mesh
    from pytorch_distributed_training_tpu.parallel.tensor import (
        tp_state_shardings,
        zero_grad_shardings,
    )

    tokens, labels = _data(seed=11)
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    model = _model()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mesh = make_3d_mesh(1, 2)  # data 4 x model 2

    def run(zero, grad_accum):
        state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
        state = jax.device_put(state, tp_state_shardings(state, mesh, zero=zero))
        step = build_tp_lm_train_step(
            model, opt, lr_fn, mesh, donate=False, zero=zero,
            grad_accum=grad_accum,
        )(state)
        # two chained steps: the second consumes ZeRO-2's all-gathered params
        s, _ = step(state, tokens, labels)
        return step(s, tokens, labels)

    s_plain, l_plain = run(zero=0, grad_accum=1)
    for accum in (1, 2):
        s_z2, l_z2 = run(zero=2, grad_accum=accum)
        assert np.isclose(float(l_plain), float(l_z2), atol=1e-6), accum
        for a, b in zip(
            jax.tree_util.tree_leaves(s_plain.params),
            jax.tree_util.tree_leaves(s_z2.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )

    # the gradient sharding rule itself: every moment-shardable leaf gets a
    # data-axis dim, mirroring zero_shard_moment
    from conftest import uses_mesh_axis

    gsh = zero_grad_shardings(params, mesh)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): sh
        for path, sh in jax.tree_util.tree_flatten_with_path(gsh)[0]
    }
    for name in ("block0/attn/qkv/kernel", "block0/mlp/fc2/kernel", "tok_embedding"):
        assert uses_mesh_axis(flat[name], "data"), name


def test_zero3_sharded_params_match_plain():
    """training.zero: 3 (FSDP semantics): parameters themselves live in the
    data-scattered layout; the step must still equal plain DP exactly, with
    the live param leaves actually sharded over data."""
    from pytorch_distributed_training_tpu.parallel import make_3d_mesh
    from pytorch_distributed_training_tpu.parallel.tensor import tp_state_shardings

    tokens, labels = _data(seed=13)
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    model = _model()
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mesh = make_3d_mesh(1, 2)  # data 4 x model 2

    def run(zero):
        state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
        state = jax.device_put(state, tp_state_shardings(state, mesh, zero=zero))
        step = build_tp_lm_train_step(
            model, opt, lr_fn, mesh, donate=False, zero=zero
        )(state)
        s, _ = step(state, tokens, labels)
        return step(s, tokens, labels)  # chained: consumes sharded params

    s_plain, l_plain = run(0)
    s_z3, l_z3 = run(3)
    assert np.isclose(float(l_plain), float(l_z3), atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_plain.params),
        jax.tree_util.tree_leaves(s_z3.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )

    from conftest import uses_mesh_axis

    flat_p = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(s_z3.params)[0]
    }
    # big 2-D params (and the embedding) carry the data axis; under TP the
    # column/row kernels carry BOTH axes
    for name in ("tok_embedding", "block0/attn/qkv/kernel",
                 "block0/mlp/fc2/kernel", "head/kernel"):
        assert uses_mesh_axis(flat_p[name].sharding, "data"), name
    assert uses_mesh_axis(flat_p["block0/attn/qkv/kernel"].sharding, "model")


# ----------------------------------------------------------------------
# GSPMD flash island (round 5, VERDICT r4 #2): with a mesh hint the
# TP/ZeRO steps run Pallas flash attention inside a shard_map island
# instead of the O(S^2) einsum.  Forced on the CPU mesh via
# PDT_FLASH_GSPMD_INTERPRET; the oracle is the same single-device einsum
# reference, so the island's resharding AND the kernel numerics are both
# pinned.  Real-TPU throughput evidence: PERF.md round 5.
# ----------------------------------------------------------------------
@pytest.mark.parametrize("topology", ["tp4", "3d_sp2_tp2", "zero1_dp8"])
def test_gspmd_flash_island_matches_single_device(topology, monkeypatch):
    from pytorch_distributed_training_tpu.ops import attention as attn_mod
    from pytorch_distributed_training_tpu.parallel import make_3d_mesh

    monkeypatch.setenv("PDT_FLASH_GSPMD_INTERPRET", "1")
    calls = []
    real_island = attn_mod._gspmd_flash

    def counting_island(*args, **kwargs):
        calls.append(1)
        return real_island(*args, **kwargs)

    monkeypatch.setattr(attn_mod, "_gspmd_flash", counting_island)

    seq = 128  # >= the flash gate's s % 128 == 0 minimum
    rng = np.random.default_rng(21)
    tokens_np = rng.integers(0, VOCAB, (BATCH, seq + 1)).astype(np.int32)
    tokens, labels = jnp.asarray(tokens_np[:, :-1]), jnp.asarray(tokens_np[:, 1:])
    opt = SGD(lr=0.05, momentum=0.9, weight_decay=1e-4)
    lr_fn = multi_step_lr(0.05, [], 0.1)
    model = TransformerLM(
        vocab_size=VOCAB, max_len=seq, embed_dim=32, depth=2, num_heads=4,
        seq_axis=None,
    )
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def ref_loss(p):
        logits = model.apply({"params": p}, tokens)
        return cross_entropy_loss(logits.reshape(-1, VOCAB), labels.reshape(-1))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    params_ref, _ = opt.update(grads_ref, opt.init(params), params, 0.05)
    assert not calls  # reference path must NOT take the island

    from pytorch_distributed_training_tpu.parallel.tensor import tp_state_shardings

    mesh, zero = {
        "tp4": (lambda: (make_mesh(model_parallelism=4), 0)),
        "3d_sp2_tp2": (lambda: (make_3d_mesh(2, 2), 0)),
        # the bench-measurable GSPMD config: pure ZeRO-1 at tp=1
        "zero1_dp8": (lambda: (make_mesh(model_parallelism=1), 1)),
    }[topology]()
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, tp_state_shardings(state, mesh, zero=zero))
    step = build_tp_lm_train_step(model, opt, lr_fn, mesh, donate=False, zero=zero)(
        state
    )
    state2, loss_tp = step(state, tokens, labels)

    assert calls, "island was not taken"
    assert np.isclose(float(loss_tp), float(loss_ref), atol=2e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_ref),
        jax.tree_util.tree_leaves(state2.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)
