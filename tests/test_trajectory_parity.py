"""Training-trajectory parity vs torch (round-2 VERDICT missing #1).

The round-2 weight-port test (tests/test_torch_port.py) proves the forward
functions agree at one point in weight space.  This test proves the
*training dynamics* track torch: port identical weights, feed identical
batches, run the full reference recipe (SGD + momentum + coupled weight
decay, train-mode BN with running-stat updates, per-iteration multi_step LR
with a milestone INSIDE the run — /root/reference/train_distributed.py:267-299
and config/ResNet50.yml:7-24 semantics) in torch CPU and in our compiled
SPMD step, and require the per-step losses and the final params + BN
running stats to agree.

Run on a 1-device mesh so both sides are a single sequential float32
program — the residual is XLA-vs-torch op-level reduction-order noise,
which an untrained-BN net amplifies ~50-100x per step (each step's param
perturbation re-enters the next forward; same phenomenon measured in
tests/test_multihost.py).  The bounds are therefore tiered: tight where a
semantic bug would show instantly (steps 0-2: rtol 1e-3, float noise is
~1e-5 there) and scaled with the measured Lyapunov growth after.  The
canary tests prove the tiers have teeth: recipes with momentum dropped or
the LR milestone ignored violate the same bounds.

Per-step optimizer math (wd coupling, dampening, nesterov, first-step
buffer) is separately pinned BITWISE by tests/test_optimizers.py; this
oracle covers the composition: BN batch-stat updates + schedule stepping +
momentum state threading through the compiled step.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.engine import (
    build_train_step,
    init_train_state,
)
from pytorch_distributed_training_tpu.models import get_model
from pytorch_distributed_training_tpu.models.torch_port import (
    import_torch_resnet_state_dict,
)
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_training_tpu.schedulers import multi_step_lr

from test_torch_port import TorchBasicBlock, TorchResNet

# Full reference-recipe shape at toy scale: momentum + coupled WD + a LR
# milestone mid-run.  lr is kept small and the data class-structured
# (learnable) so gradients cohere and the float-noise Lyapunov rate stays
# low — with lr 0.01 on pure-noise data the measured amplification was
# ~50-200x/step, drowning any semantic signal past step 3; at this recipe
# the measured per-step relative drift is [8e-7, 3e-6, 2e-5, 2e-4, 7e-4,
# 3e-3] (calibration run, this machine), giving the tiers below 5-6x
# margins while the canary recipes overshoot them by 10-100x.
LR0, MILESTONES, GAMMA = 0.003, [2], 0.1
WD, MOMENTUM = 1e-4, 0.9
ITERS, BATCH, CLASSES, SIZE = 6, 8, 10, 32


def _batches():
    rng = np.random.default_rng(7)
    class_means = rng.standard_normal((CLASSES, 3)).astype(np.float32)
    labels = rng.integers(0, CLASSES, (ITERS, BATCH)).astype(np.int32)
    imgs = (
        class_means[labels].reshape(ITERS, BATCH, 1, 1, 3)
        + 0.3 * rng.standard_normal((ITERS, BATCH, SIZE, SIZE, 3))
    ).astype(np.float32)
    return imgs, labels


def _torch_trajectory(tmodel, imgs, labels):
    opt = torch.optim.SGD(
        tmodel.parameters(), lr=LR0, momentum=MOMENTUM, weight_decay=WD
    )
    sched = torch.optim.lr_scheduler.MultiStepLR(
        opt, milestones=MILESTONES, gamma=GAMMA
    )
    loss_fn = torch.nn.CrossEntropyLoss()
    tmodel.train()
    losses = []
    for i in range(ITERS):
        x = torch.from_numpy(np.transpose(imgs[i], (0, 3, 1, 2))).contiguous()
        y = torch.from_numpy(labels[i]).long()
        opt.zero_grad()
        loss = loss_fn(tmodel(x), y)
        loss.backward()
        opt.step()
        sched.step()  # per-iteration, reference :299
        losses.append(float(loss.detach()))
    return losses


def _ported_state(tmodel, optimizer):
    model = get_model("ResNet18", num_classes=CLASSES)
    state = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    variables = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tmodel.state_dict(),
    )
    return model, state.replace(
        params=jax.tree.map(jnp.asarray, variables["params"]),
        batch_stats=jax.tree.map(jnp.asarray, variables["batch_stats"]),
    )


def _jax_trajectory(imgs, labels, momentum=MOMENTUM, gamma=GAMMA):
    """Our compiled-step trajectory; momentum/gamma overridable so the
    canary tests can run a deliberately wrong recipe through the SAME
    harness."""
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    opt = SGD(lr=LR0, momentum=momentum, weight_decay=WD)
    model, state = _ported_state(tmodel, opt)
    # 1-device mesh: pmean/psum are identities, the step is the same
    # sequential program torch ran (no cross-device reduction-order noise)
    mesh = make_mesh(devices=jax.devices()[:1])
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_train_step(
        model, opt, multi_step_lr(LR0, MILESTONES, gamma), mesh,
        sync_bn=False, donate=False,
    )
    losses = []
    for i in range(ITERS):
        img = jax.device_put(imgs[i], batch_sharding(mesh, 4))
        lab = jax.device_put(labels[i], batch_sharding(mesh, 1))
        state, loss = step(state, img, lab)
        losses.append(float(loss))
    return losses, state


def test_training_trajectory_matches_torch():
    imgs, labels = _batches()
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    torch_losses = _torch_trajectory(tmodel, imgs, labels)
    jax_losses, state = _jax_trajectory(imgs, labels)

    # semantic-bug window: any wrong decay/momentum/LR/BN-stat term is
    # O(1e-2..1) relative by step 2; measured float noise there is ~2e-5
    np.testing.assert_allclose(jax_losses[:3], torch_losses[:3], rtol=1e-4)
    # full horizon, spanning the LR-milestone switch at iter 2 (a missed
    # gamma or per-epoch scheduler stepping blows this by 10x — canary)
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-2)

    # final STATE parity: port torch's post-training state_dict (params AND
    # BN running stats — the BN-momentum/unbiased-var update dynamics) and
    # compare against our final state, leaf by leaf
    final = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tmodel.state_dict(),
    )
    got = {"params": state.params, "batch_stats": state.batch_stats}
    flat_want = jax.tree_util.tree_flatten_with_path(final)[0]
    flat_got = jax.tree_util.tree_flatten_with_path(got)[0]
    assert len(flat_want) == len(flat_got)
    for (path_w, want), (path_g, have) in zip(flat_want, flat_got):
        assert path_w == path_g
        np.testing.assert_allclose(
            np.asarray(have),
            np.asarray(want),
            atol=1e-2,
            rtol=1e-2,
            err_msg=jax.tree_util.keystr(path_w),
        )


@pytest.mark.parametrize(
    "wrong",
    [
        {"momentum": 0.0},  # momentum dropped: diverges from step 2 on
        {"gamma": 1.0},  # LR milestone ignored: diverges after iter 2
    ],
    ids=["no-momentum", "no-lr-drop"],
)
def test_trajectory_canary_catches_wrong_recipe(wrong):
    """The tolerance tiers have teeth: a deliberately wrong recipe run
    through the same harness must violate the bounds the real recipe
    satisfies — i.e. the oracle distinguishes recipes, it doesn't just
    accept anything that trains."""
    imgs, labels = _batches()
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    torch_losses = _torch_trajectory(tmodel, imgs, labels)
    jax_losses, _ = _jax_trajectory(imgs, labels, **wrong)
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(jax_losses[:3], torch_losses[:3], rtol=1e-4)
        np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-2)
