"""Training-trajectory parity vs torch (round-2 VERDICT missing #1).

The round-2 weight-port test (tests/test_torch_port.py) proves the forward
functions agree at one point in weight space.  This test proves the
*training dynamics* track torch: port identical weights, feed identical
batches, run the full reference recipe (SGD + momentum + coupled weight
decay, train-mode BN with running-stat updates, per-iteration multi_step LR
with a milestone INSIDE the run — /root/reference/train_distributed.py:267-299
and config/ResNet50.yml:7-24 semantics) in torch CPU and in our compiled
SPMD step, and require the per-step losses and the final params + BN
running stats to agree.

Run on a 1-device mesh so both sides are a single sequential float32
program — the residual is XLA-vs-torch op-level reduction-order noise,
which an untrained-BN net amplifies ~50-100x per step (each step's param
perturbation re-enters the next forward; same phenomenon measured in
tests/test_multihost.py).  The bounds are therefore tiered: tight where a
semantic bug would show instantly (steps 0-2: rtol 1e-3, float noise is
~1e-5 there) and scaled with the measured Lyapunov growth after.  The
canary tests prove the tiers have teeth: recipes with momentum dropped or
the LR milestone ignored violate the same bounds.

Per-step optimizer math (wd coupling, dampening, nesterov, first-step
buffer) is separately pinned BITWISE by tests/test_optimizers.py; this
oracle covers the composition: BN batch-stat updates + schedule stepping +
momentum state threading through the compiled step.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.engine import (
    build_train_step,
    init_train_state,
)
from pytorch_distributed_training_tpu.models import get_model
from pytorch_distributed_training_tpu.models.torch_port import (
    import_torch_resnet_state_dict,
)
from pytorch_distributed_training_tpu.optimizers import SGD
from pytorch_distributed_training_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_training_tpu.schedulers import multi_step_lr

from test_torch_port import TorchBasicBlock, TorchResNet

# Full reference-recipe shape at toy scale: momentum + coupled WD + a LR
# milestone mid-run.  lr is kept small and the data class-structured
# (learnable) so gradients cohere and the float-noise Lyapunov rate stays
# low — with lr 0.01 on pure-noise data the measured amplification was
# ~50-200x/step, drowning any semantic signal past step 3; at this recipe
# the measured per-step relative drift is [8e-7, 3e-6, 2e-5, 2e-4, 7e-4,
# 3e-3] (calibration run, this machine), giving the tiers below 5-6x
# margins while the canary recipes overshoot them by 10-100x.
LR0, MILESTONES, GAMMA = 0.003, [2], 0.1
WD, MOMENTUM = 1e-4, 0.9
ITERS, BATCH, CLASSES, SIZE = 6, 8, 10, 32


def _batches():
    rng = np.random.default_rng(7)
    class_means = rng.standard_normal((CLASSES, 3)).astype(np.float32)
    labels = rng.integers(0, CLASSES, (ITERS, BATCH)).astype(np.int32)
    imgs = (
        class_means[labels].reshape(ITERS, BATCH, 1, 1, 3)
        + 0.3 * rng.standard_normal((ITERS, BATCH, SIZE, SIZE, 3))
    ).astype(np.float32)
    return imgs, labels


def _torch_trajectory(tmodel, imgs, labels):
    opt = torch.optim.SGD(
        tmodel.parameters(), lr=LR0, momentum=MOMENTUM, weight_decay=WD
    )
    sched = torch.optim.lr_scheduler.MultiStepLR(
        opt, milestones=MILESTONES, gamma=GAMMA
    )
    loss_fn = torch.nn.CrossEntropyLoss()
    tmodel.train()
    losses = []
    for i in range(ITERS):
        x = torch.from_numpy(np.transpose(imgs[i], (0, 3, 1, 2))).contiguous()
        y = torch.from_numpy(labels[i]).long()
        opt.zero_grad()
        loss = loss_fn(tmodel(x), y)
        loss.backward()
        opt.step()
        sched.step()  # per-iteration, reference :299
        losses.append(float(loss.detach()))
    return losses


def _ported_state(tmodel, optimizer):
    model = get_model("ResNet18", num_classes=CLASSES)
    state = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    variables = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tmodel.state_dict(),
    )
    return model, state.replace(
        params=jax.tree.map(jnp.asarray, variables["params"]),
        batch_stats=jax.tree.map(jnp.asarray, variables["batch_stats"]),
    )


def _jax_trajectory(imgs, labels, momentum=MOMENTUM, gamma=GAMMA):
    """Our compiled-step trajectory; momentum/gamma overridable so the
    canary tests can run a deliberately wrong recipe through the SAME
    harness."""
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    opt = SGD(lr=LR0, momentum=momentum, weight_decay=WD)
    model, state = _ported_state(tmodel, opt)
    # 1-device mesh: pmean/psum are identities, the step is the same
    # sequential program torch ran (no cross-device reduction-order noise)
    mesh = make_mesh(devices=jax.devices()[:1])
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_train_step(
        model, opt, multi_step_lr(LR0, MILESTONES, gamma), mesh,
        sync_bn=False, donate=False,
    )
    losses = []
    for i in range(ITERS):
        img = jax.device_put(imgs[i], batch_sharding(mesh, 4))
        lab = jax.device_put(labels[i], batch_sharding(mesh, 1))
        state, loss = step(state, img, lab)
        losses.append(float(loss))
    return losses, state


@pytest.mark.quick
def test_training_trajectory_matches_torch():
    imgs, labels = _batches()
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    torch_losses = _torch_trajectory(tmodel, imgs, labels)
    jax_losses, state = _jax_trajectory(imgs, labels)

    # semantic-bug window: any wrong decay/momentum/LR/BN-stat term is
    # O(1e-2..1) relative by step 2; measured float noise there is ~2e-5
    np.testing.assert_allclose(jax_losses[:3], torch_losses[:3], rtol=1e-4)
    # full horizon, spanning the LR-milestone switch at iter 2 (a missed
    # gamma or per-epoch scheduler stepping blows this by 10x — canary)
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-2)

    # final STATE parity: port torch's post-training state_dict (params AND
    # BN running stats — the BN-momentum/unbiased-var update dynamics) and
    # compare against our final state, leaf by leaf
    final = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tmodel.state_dict(),
    )
    got = {"params": state.params, "batch_stats": state.batch_stats}
    flat_want = jax.tree_util.tree_flatten_with_path(final)[0]
    flat_got = jax.tree_util.tree_flatten_with_path(got)[0]
    assert len(flat_want) == len(flat_got)
    for (path_w, want), (path_g, have) in zip(flat_want, flat_got):
        assert path_w == path_g
        np.testing.assert_allclose(
            np.asarray(have),
            np.asarray(want),
            atol=1e-2,
            rtol=1e-2,
            err_msg=jax.tree_util.keystr(path_w),
        )


@pytest.mark.parametrize(
    "wrong",
    [
        {"momentum": 0.0},  # momentum dropped: diverges from step 2 on
        {"gamma": 1.0},  # LR milestone ignored: diverges after iter 2
    ],
    ids=["no-momentum", "no-lr-drop"],
)
@pytest.mark.slow
def test_trajectory_canary_catches_wrong_recipe(wrong):
    """The tolerance tiers have teeth: a deliberately wrong recipe run
    through the same harness must violate the bounds the real recipe
    satisfies — i.e. the oracle distinguishes recipes, it doesn't just
    accept anything that trains."""
    imgs, labels = _batches()
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    torch_losses = _torch_trajectory(tmodel, imgs, labels)
    jax_losses, _ = _jax_trajectory(imgs, labels, **wrong)
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(jax_losses[:3], torch_losses[:3], rtol=1e-4)
        np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-2)


# ----------------------------------------------------------------------
# Long-horizon statistical parity (round-3 VERDICT #1, second half).
# Lockstep bounds cannot survive ~1k chaotic steps (the Lyapunov growth
# measured above); the long-horizon oracle is STATISTICAL: from the same
# torch-ported init on the same batch stream, the bf16 compiled step and
# torch f32 must converge to the same place — final-window training loss
# within a band, probe accuracy within a few points, and both far below
# the initial loss.  (The real-JPEG converged-accuracy comparison lives in
# accuracy_harness.py / PERF.md; this is its fast synthetic pin.)
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_long_horizon_bf16_statistical_parity():
    iters, batch = 400, 16
    milestone = [280]
    lr0 = 0.01
    rng = np.random.default_rng(11)
    class_means = rng.standard_normal((CLASSES, 3)).astype(np.float32)
    labels = rng.integers(0, CLASSES, (iters, batch)).astype(np.int32)
    imgs = (
        class_means[labels].reshape(iters, batch, 1, 1, 3)
        + 0.5 * rng.standard_normal((iters, batch, SIZE, SIZE, 3))
    ).astype(np.float32)
    # held-out probe: 256 samples (accuracy granularity 0.4pt; a single
    # 16-sample batch would quantize to 6.25pt steps)
    n_probe = 256
    probe_lab = rng.integers(0, CLASSES, (n_probe,)).astype(np.int32)
    probe_img = (
        class_means[probe_lab].reshape(n_probe, 1, 1, 3)
        + 0.5 * rng.standard_normal((n_probe, SIZE, SIZE, 3))
    ).astype(np.float32)

    # --- torch f32 ----------------------------------------------------
    torch.manual_seed(0)
    tmodel = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    topt = torch.optim.SGD(
        tmodel.parameters(), lr=lr0, momentum=MOMENTUM, weight_decay=WD
    )
    tsched = torch.optim.lr_scheduler.MultiStepLR(topt, milestone, GAMMA)
    loss_fn = torch.nn.CrossEntropyLoss()
    tmodel.train()
    t_losses = []
    for i in range(iters):
        x = torch.from_numpy(np.transpose(imgs[i], (0, 3, 1, 2))).contiguous()
        y = torch.from_numpy(labels[i]).long()
        topt.zero_grad()
        loss = loss_fn(tmodel(x), y)
        loss.backward()
        topt.step()
        tsched.step()
        t_losses.append(float(loss.detach()))
    tmodel.eval()
    with torch.no_grad():
        t_acc = float(
            (
                tmodel(
                    torch.from_numpy(np.transpose(probe_img, (0, 3, 1, 2)))
                ).argmax(1).numpy()
                == probe_lab
            ).mean()
        ) * 100

    # --- ours, bf16 compute (f32 params/BN stats) ---------------------
    torch.manual_seed(0)
    tw = TorchResNet(TorchBasicBlock, [2, 2, 2, 2], num_classes=CLASSES)
    opt = SGD(lr=lr0, momentum=MOMENTUM, weight_decay=WD)
    model = get_model("ResNet18", num_classes=CLASSES, dtype=jnp.bfloat16)
    state = init_train_state(
        model, opt, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    variables = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tw.state_dict(),
    )
    state = state.replace(
        params=jax.tree.map(jnp.asarray, variables["params"]),
        batch_stats=jax.tree.map(jnp.asarray, variables["batch_stats"]),
    )
    mesh = make_mesh(devices=jax.devices()[:1])
    state = jax.device_put(state, replicated_sharding(mesh))
    step = build_train_step(
        model, opt, multi_step_lr(lr0, milestone, GAMMA), mesh,
        sync_bn=False, donate=False,
    )
    j_losses = []
    for i in range(iters):
        img = jax.device_put(imgs[i], batch_sharding(mesh, 4))
        lab = jax.device_put(labels[i], batch_sharding(mesh, 1))
        state, loss = step(state, img, lab)
        j_losses.append(float(loss))
    from pytorch_distributed_training_tpu.engine import build_eval_step

    eval_step = build_eval_step(model, mesh)
    _, j_acc, _ = eval_step(
        state,
        jax.device_put(probe_img, batch_sharding(mesh, 4)),
        jax.device_put(probe_lab, batch_sharding(mesh, 1)),
    )
    j_acc = float(j_acc)

    # Statistical agreement via ROBUST statistics: per-step losses at
    # convergence are spiky (individual steps span 0.003..1.9 on this
    # recipe), so window MEANS are dominated by a few spikes and genuinely
    # differ 30-60% between the bf16 and f32 runs even when both are
    # converged (two calibration runs measured mean gaps of 26% and 58%
    # while probe accuracies agreed to a few points).  The pinned claims:
    # (1) both trajectories CONVERGE — tail median far below the initial
    # loss; (2) the converged models CLASSIFY the same — held-out probe
    # accuracy within 10 points.  A broken bf16 step, dropped momentum, or
    # an ignored milestone fails (1) or (2) by a wide margin; the
    # short-window canaries above pin exact-recipe drift.
    t_med = float(np.median(t_losses[-80:]))
    j_med = float(np.median(j_losses[-80:]))
    init_loss = t_losses[0]
    assert t_med < 0.25 * init_loss, f"torch did not converge: {t_med}"
    assert j_med < 0.25 * init_loss, (
        f"bf16 step did not converge: tail median {j_med} vs torch {t_med} "
        f"(init {init_loss})"
    )
    assert abs(j_acc - t_acc) <= 10.0, (
        f"probe accuracy gap: ours(bf16) {j_acc:.1f}% vs torch {t_acc:.1f}%"
    )
