"""pdt-analyze battery: the tier-1 gate plus proof every pass catches its
seeded fixtures.

Layout:
  - the GATE: zero unsuppressed findings over the real package tree
    (the same invariant the CLI exit code carries);
  - per-pass clean/violation fixture pairs under tests/analysis_fixtures/
    (violation files are never imported, only parsed; the marker-pass
    fixture body is copied into a tmp tests dir under a ``test_*.py``
    name so pytest never collects the seeded violations);
  - suppression and baseline round-trips;
  - the JSON reporter schema pin;
  - the collective-order per-family extraction oracle (recorded in
    PERF.md as the baseline for the step-family unification work);
  - regression pins for the real findings this analyzer surfaced and
    fixed (watchdog fire counter, scheduler active(), elastic beat lock);
  - the v2 inference passes: thread-safety re-detecting both PR 8 races
    from fixtures WITHOUT annotations, resource-lifecycle exception-edge
    leaks, and the generated config schema validating the shipped YAMLs.
"""
import ast
import json
import pathlib
import shutil
import subprocess
import sys

import pytest

from pytorch_distributed_training_tpu import analysis
from pytorch_distributed_training_tpu.analysis import core, report
from pytorch_distributed_training_tpu.analysis.collectives import (
    CollectiveOrderPass,
    extract_collective_sequences,
)
from pytorch_distributed_training_tpu.analysis.configschema import (
    ConfigSchemaPass,
    extract_schema,
    schema_as_json,
)
from pytorch_distributed_training_tpu.analysis.conventions import MarkerConventionPass
from pytorch_distributed_training_tpu.analysis.donation import DonationSafetyPass
from pytorch_distributed_training_tpu.analysis.lifecycle import ResourceLifecyclePass
from pytorch_distributed_training_tpu.analysis.locks import LockDisciplinePass
from pytorch_distributed_training_tpu.analysis.purity import TracePurityPass
from pytorch_distributed_training_tpu.analysis.threads import ThreadSafetyPass

REPO = pathlib.Path(__file__).parent.parent
PKG = REPO / "pytorch_distributed_training_tpu"
FIXTURES = pathlib.Path(__file__).parent / "analysis_fixtures"


def _fixture_findings(pass_cls, *names):
    """Run one pass over just the named fixture files."""
    ctx = core.AnalysisContext(package_root=FIXTURES, repo_root=FIXTURES.parent)
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name in names
    ]
    assert len(modules) == len(names), f"missing fixture(s) among {names}"
    return pass_cls().run(modules, ctx)


# --------------------------------------------------------------------- gate


def test_package_tree_has_zero_unsuppressed_findings():
    """THE gate: the analyzer over the real package tree is clean.  Any
    new impurity in a traced closure, naked guarded access, divergent
    collective, donation misuse, or convention break fails here."""
    result = analysis.run()
    assert not result.unsuppressed, "\n".join(
        f.format() for f in result.unsuppressed
    )
    assert result.files_scanned > 50  # the scan really covered the tree


# ----------------------------------------------------------- trace purity


def test_purity_pass_flags_seeded_violations():
    findings = _fixture_findings(TracePurityPass, "purity_violation.py")
    messages = "\n".join(f.message for f in findings)
    assert "time.time" in messages  # direct clock in a jitted def
    assert "np.random.normal" in messages  # host RNG
    assert "os.getenv" in messages  # env read via closure helper
    assert "print" in messages  # host I/O in a built step
    assert "global _STEP_COUNT" in messages  # module-global mutation
    assert "random.random" in messages  # RNG in a lax.scan body
    # the closure attribution names the helper AND its trace root
    assert any(
        "env_helper" in f.message and "step" in f.message for f in findings
    )
    assert len(findings) >= 6


def test_purity_pass_accepts_clean_fixture():
    assert _fixture_findings(TracePurityPass, "purity_clean.py") == []


# --------------------------------------------------------- lock discipline


def test_locks_pass_flags_seeded_violations():
    findings = _fixture_findings(LockDisciplinePass, "locks_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 4, msgs
    assert any("_count written" in m and "bump" in m for m in msgs)
    assert any("_count read" in m and "LeakyCounter.read" in m for m in msgs)
    # the hoisted-out-of-with read in watermark()
    assert any("_high_water read" in m and "watermark" in m for m in msgs)
    # the nested thread-target def: lock NOT held at call time
    assert any("_count written" in m and "start_worker" in m for m in msgs)


def test_locks_pass_accepts_clean_fixture():
    # _locked suffix, def-line guarded-by comment, and with-blocks all
    # count as holding the lock; __init__ is exempt
    assert _fixture_findings(LockDisciplinePass, "locks_clean.py") == []


# -------------------------------------------------------- collective order


def test_collectives_pass_flags_host_divergent_branches():
    findings = _fixture_findings(CollectiveOrderPass, "collectives_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 3, msgs
    assert any("psum" in m and "process_index" in m for m in msgs)
    assert any("all_gather" in m and "os.environ" in m for m in msgs)
    assert any("psum" in m and "process_count" in m for m in msgs)  # IfExp


def test_collectives_pass_accepts_uniform_branches():
    # config-driven branches are host-uniform: no finding
    assert _fixture_findings(CollectiveOrderPass, "collectives_clean.py") == []


def test_collective_extraction_reads_family_and_order():
    seqs = extract_collective_sequences(FIXTURES, FIXTURES.parent)
    bad = seqs["fixture-bad"]
    assert [c.op for c in bad["build_divergent_step"]] == ["psum", "pmean"]
    good = seqs["fixture-good"]
    assert [c.op for c in good["build_plain_step"]] == ["psum", "pmean"]
    assert all(c.axis == "'data'" for c in good["build_plain_step"])


# -------------------------------------------------------- donation safety


def test_donation_pass_flags_seeded_violations():
    findings = _fixture_findings(DonationSafetyPass, "donation_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 3, msgs
    assert any(
        "`state` used after being donated to `train_step`" in m for m in msgs
    )
    assert any(
        "`state` used after being donated to `apply_update`" in m for m in msgs
    )
    assert any("out of range" in m and "bad_arity_step" in m for m in msgs)


def test_donation_pass_accepts_consume_and_rebind():
    assert _fixture_findings(DonationSafetyPass, "donation_clean.py") == []


# ------------------------------------------------------- marker convention


def test_marker_pass_flags_seeded_test_violations(tmp_path):
    # the fixture body is stored under a non-test name; give it a
    # collectable name only inside the throwaway tests dir
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    shutil.copy(
        FIXTURES / "marker_violation_body.py",
        tests_dir / "test_seeded_markers.py",
    )
    ctx = core.AnalysisContext(
        package_root=FIXTURES, repo_root=tmp_path, tests_dir=tests_dir
    )
    findings = MarkerConventionPass().run([], ctx)
    msgs = [f.message for f in findings]
    assert len(findings) == 2, msgs
    assert any("test_unmarked_bench_driver" in m for m in msgs)
    assert any("test_unmarked_fault_chaos" in m for m in msgs)
    # the properly-marked twins must NOT be flagged
    assert not any("properly_marked" in m for m in msgs)


def test_marker_pass_flags_counter_stores():
    findings = _fixture_findings(
        MarkerConventionPass, "counter_store_violation.py"
    )
    counter_findings = [
        f for f in findings if "ad-hoc counter store" in f.message
    ]
    # self._counters = {} in __init__ and the module-level Counter()
    assert len(counter_findings) == 2, [f.format() for f in counter_findings]


# ----------------------------------------------------------- suppressions


def test_suppression_trailing_and_line_above_forms():
    ctx = core.AnalysisContext(package_root=FIXTURES, repo_root=FIXTURES.parent)
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name == "suppression_mix.py"
    ]
    # run through run_passes-style folding by checking is_suppressed
    findings = TracePurityPass().run(modules, ctx)
    assert len(findings) == 3  # the pass itself sees all three
    mod = modules[0]
    live = [f for f in findings if not mod.is_suppressed(f)]
    dropped = [f for f in findings if mod.is_suppressed(f)]
    assert len(live) == 1 and "raw_violation" in live[0].message
    assert len(dropped) == 2


def test_wildcard_suppression(tmp_path):
    src = (
        "import time, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + time.time()  # pdt: ignore[*] -- fixture\n"
    )
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(src)
    result = analysis.run(package_root=pkg)
    assert not result.unsuppressed
    assert len(result.suppressed) == 1


# --------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(FIXTURES / "donation_violation.py", pkg / "legacy.py")
    first = analysis.run(package_root=pkg)
    assert first.unsuppressed  # the violations are live...
    bl = tmp_path / "baseline.json"
    core.write_baseline(bl, first.unsuppressed)
    second = analysis.run(package_root=pkg, baseline=bl)
    assert not second.unsuppressed  # ...then adopted by the baseline
    assert len(second.baselined) == len(first.unsuppressed)
    # baseline keys are line-independent: prepending a comment moves
    # every line but resurrects nothing
    legacy = pkg / "legacy.py"
    legacy.write_text("# moved\n" + legacy.read_text())
    third = analysis.run(package_root=pkg, baseline=bl)
    assert not third.unsuppressed


def test_baseline_rejects_unknown_version(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text(json.dumps({"version": 99, "findings": []}))
    with pytest.raises(ValueError):
        core.load_baseline(bad)


# ------------------------------------------------------------ JSON schema


def test_json_reporter_schema_pin():
    result = analysis.run(rules=["donation-safety"])
    payload = report.json_payload(result)
    assert payload["version"] == 1
    assert set(payload) == {"version", "findings", "summary"}
    assert set(payload["summary"]) == {
        "unsuppressed",
        "suppressed",
        "baselined",
        "by_rule",
        "files_scanned",
        "wall_s",
    }
    for f in payload["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "message"}
    # and it must be round-trippable text
    assert json.loads(report.render_json(result)) == payload


def test_unknown_rule_is_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        analysis.run(rules=["no-such-rule"])


# ------------------------------------------------------------------- CLI


def test_cli_exits_zero_on_package_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "pytorch_distributed_training_tpu.analysis"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pdt-analyze:" in proc.stdout


def test_cli_exits_one_on_violations_and_emits_json(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(FIXTURES / "purity_violation.py", pkg / "mod.py")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytorch_distributed_training_tpu.analysis",
            "--root",
            str(pkg),
            "--format",
            "json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["summary"]["unsuppressed"] > 0


# ----------------------------------------- collective-order family oracle


def test_collective_order_oracle_matches_perf_md():
    """The per-family collective sequences of the four step families,
    pinned as the baseline oracle for the step-family unification work
    (ROADMAP item 3, recorded in PERF.md).  A refactor that unifies the
    step builders must reproduce these sequences EXACTLY — reordering or
    dropping a collective changes multi-host semantics."""
    seqs = extract_collective_sequences(PKG)
    assert set(seqs) == {"dp", "sp", "tp", "pp", "comm"}

    def ops(family, builder):
        return [c.op for c in seqs[family][builder]]

    # PR 11: the dp/sp builders gained the comm.overlap branch — one extra
    # lexical pmean/psum each (the explicit post-backward reduction +
    # loss reduction; config-uniform `if overlap:` branches, so the pass
    # sees both arms).  The default-off path still traces the original
    # sequence; bitwise parity is pinned in tests/test_comm_overlap.py.
    assert ops("dp", "build_train_step") == ["pmean", "pmean", "pmean"]
    assert ops("dp", "build_eval_step") == ["pmean"]
    assert ops("dp", "build_eval_step_exact") == ["psum"]
    assert ops("sp", "build_lm_train_step") == ["psum", "psum", "psum"]
    assert ops("sp", "build_lm_eval_step") == ["psum", "pmean"]
    # the bucketed reducers themselves live in family "comm": plain-DP
    # reduce (psum|pmean per bucket) and the ZeRO-1 scatter/gather pair
    assert ops("comm", "reduce_gradients") == ["psum", "pmean"]
    assert ops("comm", "zero1_update") == ["psum_scatter", "all_gather"]
    assert ops("pp", "build_pp_lm_train_step") == [
        "ppermute",
        "psum",
        "ppermute",
        "ppermute",
        "psum",
    ]
    assert ops("pp", "build_pp_lm_eval_step") == [
        "ppermute",
        "psum",
        "psum",
        "psum",
    ]
    # TP is GSPMD-compiled: the partitioner inserts its collectives, so
    # the static extraction legitimately sees none
    assert seqs["tp"] == {}


# ------------------------------------- regression pins for the real fixes


def _method(tree, cls_name, meth_name):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name == meth_name
                ):
                    return item
    raise AssertionError(f"{cls_name}.{meth_name} not found")


def test_watchdog_fire_counter_updates_under_lock():
    """pdt-analyze finding (fixed this PR): StepWatchdog._run bumped
    ``self.fires`` outside ``self._lock`` — a racy read-modify-write
    against any thread polling the counter.  Pin that every ``fires``
    write outside __init__ sits inside a with-block."""
    src = (PKG / "engine" / "watchdog.py").read_text()
    tree = ast.parse(src)
    run = _method(tree, "StepWatchdog", "_run")
    writes = [
        n
        for n in ast.walk(run)
        for t in (
            n.targets if isinstance(n, ast.Assign) else [n.target]
            if isinstance(n, ast.AugAssign) else []
        )
        if isinstance(t, ast.Attribute) and t.attr == "fires"
    ]
    assert writes, "the fire-count bump disappeared from _run"
    with_lines = [
        (n.lineno, n.end_lineno) for n in ast.walk(run) if isinstance(n, ast.With)
    ]
    for w in writes:
        assert any(a <= w.lineno <= b for a, b in with_lines), (
            "self.fires bumped outside the lock again"
        )
    # and the declared guard means the analyzer itself now pins this too
    ctx = core.AnalysisContext(package_root=PKG, repo_root=REPO)
    modules = [
        m
        for m in core.collect_modules(PKG, REPO)
        if m.rel.endswith("engine/watchdog.py")
    ]
    assert LockDisciplinePass().run(modules, ctx) == []


def test_scheduler_active_snapshots_under_condition():
    """pdt-analyze audit finding (fixed this PR): ContinuousScheduler
    .active() read the slot list without the condition while
    _fail_inflight rebinds it wholesale under the lock.  Pin that the
    slot scan sits inside ``with self._cond``."""
    src = (PKG / "serving" / "scheduler.py").read_text()
    active = _method(ast.parse(src), "ContinuousScheduler", "active")
    withs = [n for n in ast.walk(active) if isinstance(n, ast.With)]
    assert withs, "active() no longer takes the condition"
    guarded_src = ast.unparse(withs[0])
    assert "self._cond" in guarded_src and "_slots" in guarded_src


def test_framework_registers_all_eight_passes():
    rules = {cls.rule for cls in analysis.ALL_PASSES}
    assert rules == {
        "trace-purity",
        "lock-discipline",
        "collective-order",
        "donation-safety",
        "marker-convention",
        "thread-safety",
        "resource-lifecycle",
        "config-schema",
    }


def test_unregistered_pass_fails_the_registration_pin(tmp_path):
    """A new AnalysisPass subclass that never lands in ALL_PASSES is
    itself a marker-convention finding — the framework refuses to let a
    pass exist that runs nowhere."""
    pkg = tmp_path / "pkg"
    ana = pkg / "analysis"
    ana.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (ana / "__init__.py").write_text("ALL_PASSES = ()\n")
    (ana / "rogue.py").write_text(
        "from ..core import AnalysisPass\n\n\n"
        "class RoguePass(AnalysisPass):\n"
        "    rule = 'rogue'\n"
    )
    ctx = core.AnalysisContext(package_root=pkg, repo_root=tmp_path)
    modules = core.collect_modules(pkg, tmp_path)
    findings = MarkerConventionPass().run(modules, ctx)
    assert any(
        "RoguePass" in f.message and "ALL_PASSES" in f.message for f in findings
    )


# --------------------------------------------- serving fault-tolerance gate


def test_cli_clean_on_serving_modules():
    """PR 9 gate: the serving tree (scheduler + resilience + kv pool +
    engine) passes every analysis pass — in particular lock-discipline
    over the supervisor's cross-thread restart counters and the
    scheduler's cond-guarded queue/drain/hang state."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytorch_distributed_training_tpu.analysis",
            "--root",
            str(PKG / "serving"),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_serving_recovery_state_is_lock_annotated():
    """The cross-thread recovery state must stay VISIBLY guarded: the
    lock-discipline pass keys off ``# guarded by:`` annotations, so
    silently dropping them would also silently drop its coverage of the
    supervisor and scheduler."""
    sup = (PKG / "serving" / "resilience.py").read_text()
    assert sup.count("# guarded by: self._lock") >= 2  # _restarts, _exhausted
    sched = (PKG / "serving" / "scheduler.py").read_text()
    # queue/close/drain/hang state all ride the scheduler condition
    assert sched.count("# guarded by: self._cond") >= 5
    # the fleet router's shared state (outstanding requests, down-set,
    # failover queue, sticky map) rides the router lock — and the
    # declarations are what lets the lock-discipline pass police every
    # submit/deliver/failover path against it
    router = (PKG / "serving" / "router.py").read_text()
    assert router.count("# guarded by: self._lock") >= 6


# ------------------------------------ v2: inferred-lockset thread safety


def test_thread_pass_redetects_both_pr8_races_without_annotations():
    """THE v2 acceptance bar: the fixtures replay the watchdog fire-count
    bump and the scheduler slot snapshot — the two real races PR 8's
    annotation-based pass caught — with every ``# guarded by:`` comment
    stripped.  Inference alone must flag both."""
    src = (FIXTURES / "threads_violation.py").read_text()
    assert "guarded by" not in src  # nothing for the annotation pass to key off
    findings = _fixture_findings(ThreadSafetyPass, "threads_violation.py")
    messages = "\n".join(f.message for f in findings)
    assert "self.fires in RacyWatchdog" in messages  # PR 8 race shape #1
    assert "thread:_run" in messages
    assert "self._slots in RacyScheduler" in messages  # PR 8 race shape #2
    assert "thread:_loop" in messages
    # the lock-ridden queue in RacyScheduler must NOT be flagged: both
    # sides take self._lock, and the inferred locksets intersect
    assert "_queue" not in messages


def test_thread_pass_verifies_confinement_declarations():
    findings = _fixture_findings(ThreadSafetyPass, "threads_violation.py")
    messages = "\n".join(f.message for f in findings)
    # naming a root that does not exist is itself a finding...
    assert "_nonexistent" in messages
    # ...and so is an api-side write into loop-confined state
    assert "written from root api (in reset)" in messages
    assert len(findings) == 4  # the two races + the two confinement breaks


def test_thread_pass_clean_fixture_stays_clean():
    """Locked, confined-and-honored, and message-passing twins of the
    racy shapes produce zero findings."""
    assert _fixture_findings(ThreadSafetyPass, "threads_clean.py") == []


def test_thread_suppression_round_trip(tmp_path):
    """``# pdt: ignore[thread-safety]`` on the write line suppresses the
    race finding and is accounted as suppressed, not dropped."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "racy.py").write_text(
        "import threading\n\n\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "        self._t = threading.Thread(target=self._run, daemon=True)\n"
        "        self._t.start()\n\n"
        "    def _run(self):\n"
        "        self.n += 1  # pdt: ignore[thread-safety]\n\n"
        "    def read(self):\n"
        "        return self.n\n"
    )
    result = analysis.run(package_root=pkg, rules=["thread-safety"])
    assert result.unsuppressed == []
    assert len(result.suppressed) == 1


# ----------------------------------------------- v2: resource lifecycle


def test_lifecycle_pass_flags_seeded_leaks():
    findings = _fixture_findings(ResourceLifecyclePass, "lifecycle_violation.py")
    messages = "\n".join(f.message for f in findings)
    # the in-flight-future bug class: a call between acquire and resolve
    # can raise, leaving the caller blocked on a future nobody resolves
    assert "leak_on_exception_edge" in messages and "exception edge" in messages
    assert "definite_future_leak" in messages and "never reaches" in messages
    assert "unjoined_worker" in messages and "join" in messages
    assert "file_leak_on_exception" in messages
    assert len(findings) == 4


def test_lifecycle_clean_fixture_stays_clean():
    """finally/except release, ownership escapes, daemon exemption and
    with-managed handles are all recognized as safe."""
    assert _fixture_findings(ResourceLifecyclePass, "lifecycle_clean.py") == []


def test_new_rules_baseline_round_trip(tmp_path):
    """A baseline written against the v2 findings silences exactly those
    findings on re-run — adoption path for a tree not yet clean."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name in ("threads_violation.py", "lifecycle_violation.py"):
        shutil.copy(FIXTURES / name, pkg / name)
    rules = ["thread-safety", "resource-lifecycle"]
    first = analysis.run(package_root=pkg, rules=rules)
    assert len(first.unsuppressed) == 8
    baseline = tmp_path / "baseline.json"
    core.write_baseline(baseline, first.unsuppressed)
    second = analysis.run(package_root=pkg, rules=rules, baseline=baseline)
    assert second.unsuppressed == []
    assert len(second.baselined) == 8


# --------------------------------------------------- v2: config schema


def _configschema_findings(fixture, config_dirname):
    ctx = core.AnalysisContext(
        package_root=FIXTURES,
        repo_root=FIXTURES.parent,
        config_dir=FIXTURES / config_dirname,
    )
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name == fixture
    ]
    assert modules, f"missing fixture {fixture}"
    return ConfigSchemaPass().run(modules, ctx)


def test_configschema_flags_unknown_key_and_type_mismatch():
    findings = _configschema_findings("configschema_parser.py", "configs_violation")
    messages = "\n".join(f.message for f in findings)
    assert "unknown key training.widget.treshold" in messages  # the typo
    assert "type mismatch for training.widget.mode" in messages
    assert len(findings) == 2
    # both findings point into the YAML file, at the offending lines
    assert all(f.path.endswith("bad.yml") for f in findings)


def test_configschema_clean_yaml_validates():
    assert _configschema_findings("configschema_parser.py", "configs_clean") == []


def test_configschema_flags_dead_allowset_key():
    findings = _configschema_findings("configschema_dead_key.py", "no_such_configs")
    assert len(findings) == 1
    assert "retired_knob" in findings[0].message
    assert "dead key" in findings[0].message
    assert findings[0].path.endswith("configschema_dead_key.py")


def test_configschema_extraction_shape():
    """The generated schema records section closure, key types and
    defaults — the machine-readable config reference ``--schema`` dumps."""
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name == "configschema_parser.py"
    ]
    dump = schema_as_json(extract_schema(modules))
    widget = dump["training.widget"]
    assert widget["closed"] is True
    assert set(widget["keys"]) == {"enabled", "threshold", "mode"}
    assert widget["keys"]["threshold"]["type"] == "float"
    assert widget["keys"]["mode"]["type"] == "str"


def test_shipped_configs_validate_against_generated_schema():
    """All shipped config/*.yml files validate against the schema
    inferred from the topology/from_config parsing surface — the
    config-schema slice of the tier-1 gate, pinned explicitly."""
    ctx = core.AnalysisContext(package_root=PKG, repo_root=REPO)
    modules = core.collect_modules(PKG, REPO)
    findings = ConfigSchemaPass().run(modules, ctx)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert len(list((REPO / "config").glob("*.yml"))) == 13
    # and the real schema covers the sections the YAMLs actually use
    dump = schema_as_json(extract_schema(modules))
    for section in ("training", "serving.scheduler", "training.checkpoint"):
        assert section in dump, f"schema lost the {section} section"


def test_cli_schema_flag_dumps_json():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytorch_distributed_training_tpu.analysis",
            "--schema",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    dump = json.loads(proc.stdout)
    assert "training" in dump and "serving.fleet" in dump
    assert dump["serving.fleet"]["closed"] is True  # the dict-pop idiom


# ----------------------------- regression pin: elastic heartbeat beat lock


def test_elastic_generation_and_seq_update_under_beat_lock():
    """pdt-analyze v2 finding (fixed this PR): ElasticCoordinator.start()
    bumped ``self.generation`` while the beat thread read it — and
    close() joins with a TIMEOUT, so the final stopped-beat write can
    genuinely overlap a still-live loop iteration.  Pin that the beat
    payload writes sit inside ``with self._beat_lock`` and that both
    inference and annotation passes stay clean on the module."""
    src = (PKG / "engine" / "elastic.py").read_text()
    tree = ast.parse(src)
    assert src.count("# guarded by: self._beat_lock") >= 2  # generation, _seq
    write_beat = _method(tree, "ElasticCoordinator", "_write_beat")
    withs = [n for n in ast.walk(write_beat) if isinstance(n, ast.With)]
    assert withs and "self._beat_lock" in ast.unparse(withs[0])
    assert "_seq" in ast.unparse(withs[0])  # the payload build rides the lock
    ctx = core.AnalysisContext(package_root=PKG, repo_root=REPO)
    modules = [
        m
        for m in core.collect_modules(PKG, REPO)
        if m.rel.endswith("engine/elastic.py")
    ]
    assert ThreadSafetyPass().run(modules, ctx) == []
    assert LockDisciplinePass().run(modules, ctx) == []
