"""pdt-analyze battery: the tier-1 gate plus proof every pass catches its
seeded fixtures.

Layout:
  - the GATE: zero unsuppressed findings over the real package tree
    (the same invariant the CLI exit code carries);
  - per-pass clean/violation fixture pairs under tests/analysis_fixtures/
    (violation files are never imported, only parsed; the marker-pass
    fixture body is copied into a tmp tests dir under a ``test_*.py``
    name so pytest never collects the seeded violations);
  - suppression and baseline round-trips;
  - the JSON reporter schema pin;
  - the collective-order per-family extraction oracle (recorded in
    PERF.md as the baseline for the step-family unification work);
  - regression pins for the real findings this analyzer surfaced and
    fixed (watchdog fire counter, scheduler active()).
"""
import ast
import json
import pathlib
import shutil
import subprocess
import sys

import pytest

from pytorch_distributed_training_tpu import analysis
from pytorch_distributed_training_tpu.analysis import core, report
from pytorch_distributed_training_tpu.analysis.collectives import (
    CollectiveOrderPass,
    extract_collective_sequences,
)
from pytorch_distributed_training_tpu.analysis.conventions import MarkerConventionPass
from pytorch_distributed_training_tpu.analysis.donation import DonationSafetyPass
from pytorch_distributed_training_tpu.analysis.locks import LockDisciplinePass
from pytorch_distributed_training_tpu.analysis.purity import TracePurityPass

REPO = pathlib.Path(__file__).parent.parent
PKG = REPO / "pytorch_distributed_training_tpu"
FIXTURES = pathlib.Path(__file__).parent / "analysis_fixtures"


def _fixture_findings(pass_cls, *names):
    """Run one pass over just the named fixture files."""
    ctx = core.AnalysisContext(package_root=FIXTURES, repo_root=FIXTURES.parent)
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name in names
    ]
    assert len(modules) == len(names), f"missing fixture(s) among {names}"
    return pass_cls().run(modules, ctx)


# --------------------------------------------------------------------- gate


def test_package_tree_has_zero_unsuppressed_findings():
    """THE gate: the analyzer over the real package tree is clean.  Any
    new impurity in a traced closure, naked guarded access, divergent
    collective, donation misuse, or convention break fails here."""
    result = analysis.run()
    assert not result.unsuppressed, "\n".join(
        f.format() for f in result.unsuppressed
    )
    assert result.files_scanned > 50  # the scan really covered the tree


# ----------------------------------------------------------- trace purity


def test_purity_pass_flags_seeded_violations():
    findings = _fixture_findings(TracePurityPass, "purity_violation.py")
    messages = "\n".join(f.message for f in findings)
    assert "time.time" in messages  # direct clock in a jitted def
    assert "np.random.normal" in messages  # host RNG
    assert "os.getenv" in messages  # env read via closure helper
    assert "print" in messages  # host I/O in a built step
    assert "global _STEP_COUNT" in messages  # module-global mutation
    assert "random.random" in messages  # RNG in a lax.scan body
    # the closure attribution names the helper AND its trace root
    assert any(
        "env_helper" in f.message and "step" in f.message for f in findings
    )
    assert len(findings) >= 6


def test_purity_pass_accepts_clean_fixture():
    assert _fixture_findings(TracePurityPass, "purity_clean.py") == []


# --------------------------------------------------------- lock discipline


def test_locks_pass_flags_seeded_violations():
    findings = _fixture_findings(LockDisciplinePass, "locks_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 4, msgs
    assert any("_count written" in m and "bump" in m for m in msgs)
    assert any("_count read" in m and "LeakyCounter.read" in m for m in msgs)
    # the hoisted-out-of-with read in watermark()
    assert any("_high_water read" in m and "watermark" in m for m in msgs)
    # the nested thread-target def: lock NOT held at call time
    assert any("_count written" in m and "start_worker" in m for m in msgs)


def test_locks_pass_accepts_clean_fixture():
    # _locked suffix, def-line guarded-by comment, and with-blocks all
    # count as holding the lock; __init__ is exempt
    assert _fixture_findings(LockDisciplinePass, "locks_clean.py") == []


# -------------------------------------------------------- collective order


def test_collectives_pass_flags_host_divergent_branches():
    findings = _fixture_findings(CollectiveOrderPass, "collectives_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 3, msgs
    assert any("psum" in m and "process_index" in m for m in msgs)
    assert any("all_gather" in m and "os.environ" in m for m in msgs)
    assert any("psum" in m and "process_count" in m for m in msgs)  # IfExp


def test_collectives_pass_accepts_uniform_branches():
    # config-driven branches are host-uniform: no finding
    assert _fixture_findings(CollectiveOrderPass, "collectives_clean.py") == []


def test_collective_extraction_reads_family_and_order():
    seqs = extract_collective_sequences(FIXTURES, FIXTURES.parent)
    bad = seqs["fixture-bad"]
    assert [c.op for c in bad["build_divergent_step"]] == ["psum", "pmean"]
    good = seqs["fixture-good"]
    assert [c.op for c in good["build_plain_step"]] == ["psum", "pmean"]
    assert all(c.axis == "'data'" for c in good["build_plain_step"])


# -------------------------------------------------------- donation safety


def test_donation_pass_flags_seeded_violations():
    findings = _fixture_findings(DonationSafetyPass, "donation_violation.py")
    msgs = [f.message for f in findings]
    assert len(findings) == 3, msgs
    assert any(
        "`state` used after being donated to `train_step`" in m for m in msgs
    )
    assert any(
        "`state` used after being donated to `apply_update`" in m for m in msgs
    )
    assert any("out of range" in m and "bad_arity_step" in m for m in msgs)


def test_donation_pass_accepts_consume_and_rebind():
    assert _fixture_findings(DonationSafetyPass, "donation_clean.py") == []


# ------------------------------------------------------- marker convention


def test_marker_pass_flags_seeded_test_violations(tmp_path):
    # the fixture body is stored under a non-test name; give it a
    # collectable name only inside the throwaway tests dir
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    shutil.copy(
        FIXTURES / "marker_violation_body.py",
        tests_dir / "test_seeded_markers.py",
    )
    ctx = core.AnalysisContext(
        package_root=FIXTURES, repo_root=tmp_path, tests_dir=tests_dir
    )
    findings = MarkerConventionPass().run([], ctx)
    msgs = [f.message for f in findings]
    assert len(findings) == 2, msgs
    assert any("test_unmarked_bench_driver" in m for m in msgs)
    assert any("test_unmarked_fault_chaos" in m for m in msgs)
    # the properly-marked twins must NOT be flagged
    assert not any("properly_marked" in m for m in msgs)


def test_marker_pass_flags_counter_stores():
    findings = _fixture_findings(
        MarkerConventionPass, "counter_store_violation.py"
    )
    counter_findings = [
        f for f in findings if "ad-hoc counter store" in f.message
    ]
    # self._counters = {} in __init__ and the module-level Counter()
    assert len(counter_findings) == 2, [f.format() for f in counter_findings]


# ----------------------------------------------------------- suppressions


def test_suppression_trailing_and_line_above_forms():
    ctx = core.AnalysisContext(package_root=FIXTURES, repo_root=FIXTURES.parent)
    modules = [
        m
        for m in core.collect_modules(FIXTURES, FIXTURES.parent)
        if pathlib.Path(m.rel).name == "suppression_mix.py"
    ]
    # run through run_passes-style folding by checking is_suppressed
    findings = TracePurityPass().run(modules, ctx)
    assert len(findings) == 3  # the pass itself sees all three
    mod = modules[0]
    live = [f for f in findings if not mod.is_suppressed(f)]
    dropped = [f for f in findings if mod.is_suppressed(f)]
    assert len(live) == 1 and "raw_violation" in live[0].message
    assert len(dropped) == 2


def test_wildcard_suppression(tmp_path):
    src = (
        "import time, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + time.time()  # pdt: ignore[*] -- fixture\n"
    )
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(src)
    result = analysis.run(package_root=pkg)
    assert not result.unsuppressed
    assert len(result.suppressed) == 1


# --------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(FIXTURES / "donation_violation.py", pkg / "legacy.py")
    first = analysis.run(package_root=pkg)
    assert first.unsuppressed  # the violations are live...
    bl = tmp_path / "baseline.json"
    core.write_baseline(bl, first.unsuppressed)
    second = analysis.run(package_root=pkg, baseline=bl)
    assert not second.unsuppressed  # ...then adopted by the baseline
    assert len(second.baselined) == len(first.unsuppressed)
    # baseline keys are line-independent: prepending a comment moves
    # every line but resurrects nothing
    legacy = pkg / "legacy.py"
    legacy.write_text("# moved\n" + legacy.read_text())
    third = analysis.run(package_root=pkg, baseline=bl)
    assert not third.unsuppressed


def test_baseline_rejects_unknown_version(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text(json.dumps({"version": 99, "findings": []}))
    with pytest.raises(ValueError):
        core.load_baseline(bad)


# ------------------------------------------------------------ JSON schema


def test_json_reporter_schema_pin():
    result = analysis.run(rules=["donation-safety"])
    payload = report.json_payload(result)
    assert payload["version"] == 1
    assert set(payload) == {"version", "findings", "summary"}
    assert set(payload["summary"]) == {
        "unsuppressed",
        "suppressed",
        "baselined",
        "by_rule",
        "files_scanned",
        "wall_s",
    }
    for f in payload["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "message"}
    # and it must be round-trippable text
    assert json.loads(report.render_json(result)) == payload


def test_unknown_rule_is_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        analysis.run(rules=["no-such-rule"])


# ------------------------------------------------------------------- CLI


def test_cli_exits_zero_on_package_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "pytorch_distributed_training_tpu.analysis"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pdt-analyze:" in proc.stdout


def test_cli_exits_one_on_violations_and_emits_json(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(FIXTURES / "purity_violation.py", pkg / "mod.py")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytorch_distributed_training_tpu.analysis",
            "--root",
            str(pkg),
            "--format",
            "json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["summary"]["unsuppressed"] > 0


# ----------------------------------------- collective-order family oracle


def test_collective_order_oracle_matches_perf_md():
    """The per-family collective sequences of the four step families,
    pinned as the baseline oracle for the step-family unification work
    (ROADMAP item 3, recorded in PERF.md).  A refactor that unifies the
    step builders must reproduce these sequences EXACTLY — reordering or
    dropping a collective changes multi-host semantics."""
    seqs = extract_collective_sequences(PKG)
    assert set(seqs) == {"dp", "sp", "tp", "pp", "comm"}

    def ops(family, builder):
        return [c.op for c in seqs[family][builder]]

    # PR 11: the dp/sp builders gained the comm.overlap branch — one extra
    # lexical pmean/psum each (the explicit post-backward reduction +
    # loss reduction; config-uniform `if overlap:` branches, so the pass
    # sees both arms).  The default-off path still traces the original
    # sequence; bitwise parity is pinned in tests/test_comm_overlap.py.
    assert ops("dp", "build_train_step") == ["pmean", "pmean", "pmean"]
    assert ops("dp", "build_eval_step") == ["pmean"]
    assert ops("dp", "build_eval_step_exact") == ["psum"]
    assert ops("sp", "build_lm_train_step") == ["psum", "psum", "psum"]
    assert ops("sp", "build_lm_eval_step") == ["psum", "pmean"]
    # the bucketed reducers themselves live in family "comm": plain-DP
    # reduce (psum|pmean per bucket) and the ZeRO-1 scatter/gather pair
    assert ops("comm", "reduce_gradients") == ["psum", "pmean"]
    assert ops("comm", "zero1_update") == ["psum_scatter", "all_gather"]
    assert ops("pp", "build_pp_lm_train_step") == [
        "ppermute",
        "psum",
        "ppermute",
        "ppermute",
        "psum",
    ]
    assert ops("pp", "build_pp_lm_eval_step") == [
        "ppermute",
        "psum",
        "psum",
        "psum",
    ]
    # TP is GSPMD-compiled: the partitioner inserts its collectives, so
    # the static extraction legitimately sees none
    assert seqs["tp"] == {}


# ------------------------------------- regression pins for the real fixes


def _method(tree, cls_name, meth_name):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name == meth_name
                ):
                    return item
    raise AssertionError(f"{cls_name}.{meth_name} not found")


def test_watchdog_fire_counter_updates_under_lock():
    """pdt-analyze finding (fixed this PR): StepWatchdog._run bumped
    ``self.fires`` outside ``self._lock`` — a racy read-modify-write
    against any thread polling the counter.  Pin that every ``fires``
    write outside __init__ sits inside a with-block."""
    src = (PKG / "engine" / "watchdog.py").read_text()
    tree = ast.parse(src)
    run = _method(tree, "StepWatchdog", "_run")
    writes = [
        n
        for n in ast.walk(run)
        for t in (
            n.targets if isinstance(n, ast.Assign) else [n.target]
            if isinstance(n, ast.AugAssign) else []
        )
        if isinstance(t, ast.Attribute) and t.attr == "fires"
    ]
    assert writes, "the fire-count bump disappeared from _run"
    with_lines = [
        (n.lineno, n.end_lineno) for n in ast.walk(run) if isinstance(n, ast.With)
    ]
    for w in writes:
        assert any(a <= w.lineno <= b for a, b in with_lines), (
            "self.fires bumped outside the lock again"
        )
    # and the declared guard means the analyzer itself now pins this too
    ctx = core.AnalysisContext(package_root=PKG, repo_root=REPO)
    modules = [
        m
        for m in core.collect_modules(PKG, REPO)
        if m.rel.endswith("engine/watchdog.py")
    ]
    assert LockDisciplinePass().run(modules, ctx) == []


def test_scheduler_active_snapshots_under_condition():
    """pdt-analyze audit finding (fixed this PR): ContinuousScheduler
    .active() read the slot list without the condition while
    _fail_inflight rebinds it wholesale under the lock.  Pin that the
    slot scan sits inside ``with self._cond``."""
    src = (PKG / "serving" / "scheduler.py").read_text()
    active = _method(ast.parse(src), "ContinuousScheduler", "active")
    withs = [n for n in ast.walk(active) if isinstance(n, ast.With)]
    assert withs, "active() no longer takes the condition"
    guarded_src = ast.unparse(withs[0])
    assert "self._cond" in guarded_src and "_slots" in guarded_src


def test_framework_registers_all_five_passes():
    rules = {cls.rule for cls in analysis.ALL_PASSES}
    assert rules == {
        "trace-purity",
        "lock-discipline",
        "collective-order",
        "donation-safety",
        "marker-convention",
    }


# --------------------------------------------- serving fault-tolerance gate


def test_cli_clean_on_serving_modules():
    """PR 9 gate: the serving tree (scheduler + resilience + kv pool +
    engine) passes every analysis pass — in particular lock-discipline
    over the supervisor's cross-thread restart counters and the
    scheduler's cond-guarded queue/drain/hang state."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytorch_distributed_training_tpu.analysis",
            "--root",
            str(PKG / "serving"),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_serving_recovery_state_is_lock_annotated():
    """The cross-thread recovery state must stay VISIBLY guarded: the
    lock-discipline pass keys off ``# guarded by:`` annotations, so
    silently dropping them would also silently drop its coverage of the
    supervisor and scheduler."""
    sup = (PKG / "serving" / "resilience.py").read_text()
    assert sup.count("# guarded by: self._lock") >= 2  # _restarts, _exhausted
    sched = (PKG / "serving" / "scheduler.py").read_text()
    # queue/close/drain/hang state all ride the scheduler condition
    assert sched.count("# guarded by: self._cond") >= 5
    # the fleet router's shared state (outstanding requests, down-set,
    # failover queue, sticky map) rides the router lock — and the
    # declarations are what lets the lock-discipline pass police every
    # submit/deliver/failover path against it
    router = (PKG / "serving" / "router.py").read_text()
    assert router.count("# guarded by: self._lock") >= 6
