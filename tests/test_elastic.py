"""Elastic multi-host recovery tests (engine/elastic.py + runner wiring).

Two tiers:

  - fast unit tests of the coordinator itself — no subprocesses, no sleeps:
    heartbeat files are aged with ``os.utime`` and the guard's blocking call
    is a ``threading.Event`` that never fires, so stale-peer detection and
    the bounded-hang guard are proved in milliseconds;
  - one ``slow`` end-to-end chaos scenario driving tests/multihost_worker.py:
    two real processes train with elastic recovery armed, one SIGKILLs
    itself mid-run (``kill_peer`` fault), the survivor must diagnose the
    death within the heartbeat timeout, write an emergency checkpoint of
    its committed state, and exit cleanly; a single-process relaunch then
    resumes from that checkpoint ACROSS the mesh reshape (dp=2x4 -> 1x8)
    mid-epoch, and the stitched loss trajectory must match an uninterrupted
    single-process run.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from pytorch_distributed_training_tpu.engine import Runner, fault
from pytorch_distributed_training_tpu.engine.elastic import (
    ElasticCoordinator,
    PeerLostError,
)
from pytorch_distributed_training_tpu.engine.topology import parse_elastic

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_ROOT, "tests", "multihost_worker.py")


# --------------------------------------------------------------- unit tier
def _coord(tmp_path, rank, n=2, hb=0.05, timeout=0.2, **kw):
    return ElasticCoordinator(
        str(tmp_path), process_index=rank, num_processes=n,
        heartbeat_interval=hb, timeout=timeout, **kw
    )


def _age_file(path, seconds):
    """Backdate a heartbeat file's mtime — the liveness clock — without
    waiting wall-clock time."""
    old = time.time() - seconds
    os.utime(path, (old, old))


def test_ctor_rejects_bad_intervals(tmp_path):
    with pytest.raises(ValueError, match="heartbeat_interval"):
        _coord(tmp_path, 0, hb=0.0)
    with pytest.raises(ValueError, match="must exceed"):
        _coord(tmp_path, 0, hb=1.0, timeout=0.5)


def test_fresh_peers_pass_and_stale_peer_is_named(tmp_path):
    c0 = _coord(tmp_path, 0)
    c1 = _coord(tmp_path, 1)
    os.makedirs(str(tmp_path), exist_ok=True)
    c0._write_beat()
    c1._write_beat()
    c0._started_at = time.monotonic()
    c0.check_peers()  # both beats fresh: no error

    _age_file(c0._path(1), 10.0)
    with pytest.raises(PeerLostError) as ei:
        c0.check_peers()
    msg = str(ei.value)
    assert "rank 1" in msg and "10." in msg and str(tmp_path) in msg
    assert ei.value.dead_ranks == (1,)
    assert ei.value.mid_step is False


def test_missing_peer_fatal_only_after_startup_grace(tmp_path):
    c0 = _coord(tmp_path, 0, startup_grace=5.0)
    os.makedirs(str(tmp_path), exist_ok=True)
    c0._write_beat()
    c0._started_at = time.monotonic()
    c0.check_peers()  # rank 1 never wrote a beat, but we're within grace
    c0._started_at = time.monotonic() - 60.0  # pretend grace has elapsed
    with pytest.raises(PeerLostError, match="startup grace"):
        c0.check_peers()


def test_generation_bump_counts_peer_restart(tmp_path):
    fault.reset_counters()
    c0 = _coord(tmp_path, 0)
    os.makedirs(str(tmp_path), exist_ok=True)
    c0._write_beat()
    c0._started_at = time.monotonic()
    c1 = _coord(tmp_path, 1).start()
    c1.close()
    assert c1.generation == 0
    c0.check_peers()  # learns generation 0
    # rank 1 restarts into the same directory: generation must bump so the
    # survivor can tell a rejoined peer from a stale file
    c1b = _coord(tmp_path, 1).start()
    c1b.close()
    assert c1b.generation == 1
    c0.check_peers()
    assert fault.counters().get("peer_restarts", 0) == 1


def test_guard_passthrough_and_exception_transparency(tmp_path):
    # single process: no watch thread at all, plain call
    solo = _coord(tmp_path, 0, n=1)
    assert solo.guard(lambda: 42) == 42
    # two processes, live peer: result and exceptions cross the side thread
    c0 = _coord(tmp_path, 0)
    c1 = _coord(tmp_path, 1)
    os.makedirs(str(tmp_path), exist_ok=True)
    c0._write_beat()
    c1._write_beat()
    c0._started_at = time.monotonic()
    assert c0.guard(lambda a, b: a + b, 2, 3) == 5
    with pytest.raises(RuntimeError, match="boom"):
        c0.guard(lambda: (_ for _ in ()).throw(RuntimeError("boom")))


def test_guard_bounds_a_hang_on_peer_death(tmp_path):
    """The tentpole promise: a call that would block forever (a collective
    wedged by a dead peer) surfaces as a diagnosed PeerLostError within
    roughly one heartbeat timeout — never an indefinite hang."""
    c0 = _coord(tmp_path, 0, hb=0.05, timeout=0.2)
    c1 = _coord(tmp_path, 1)
    os.makedirs(str(tmp_path), exist_ok=True)
    c0._write_beat()
    c1._write_beat()
    c0._started_at = time.monotonic()
    _age_file(c0._path(1), 10.0)  # the peer is already dead

    never = threading.Event()  # stands in for the wedged collective
    t0 = time.monotonic()
    with pytest.raises(PeerLostError) as ei:
        c0.guard(never.wait, 30.0, what="train step 7")
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"guard took {elapsed:.1f}s — not bounded"
    assert ei.value.mid_step is True
    assert "train step 7" in str(ei.value) and "rank 1" in str(ei.value)


def test_parse_elastic_validation():
    r = types.SimpleNamespace()
    parse_elastic(r, {})  # absent section: disabled, defaults set
    assert r.elastic_enabled is False
    with pytest.raises(ValueError, match="unknown key"):
        parse_elastic(types.SimpleNamespace(), {"elastic": {"intervall": 1}})
    with pytest.raises(ValueError, match="must exceed"):
        parse_elastic(
            types.SimpleNamespace(),
            {"elastic": {"heartbeat_interval": 2.0, "timeout": 1.0},
             "checkpoint": {"dir": "/tmp/x"}},
        )
    with pytest.raises(ValueError, match="checkpoint.dir"):
        parse_elastic(types.SimpleNamespace(), {"elastic": {"timeout": 5.0}})
    r2 = types.SimpleNamespace()
    parse_elastic(
        r2, {"elastic": {"enabled": True, "timeout": 1.0,
                         "heartbeat_interval": 0.1},
             "checkpoint": {"dir": "/tmp/x"}},
    )
    assert r2.elastic_enabled and r2.elastic_timeout == 1.0


# -------------------------------------------------------------- chaos tier
def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _spawn(rank, num_nodes, ports, out, tmp_path, tag, local_devices,
           extra_env):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(
        MH_RANK=str(rank),
        MH_NUM_NODES=str(num_nodes),
        MH_PORT=",".join(str(p) for p in ports),
        MH_PORT_FILE=str(tmp_path / f"{tag}.port"),
        MH_OUT=out,
        MH_LOCAL_DEVICES=str(local_devices),
        MH_BATCH_DIVISION="world",
        MH_TASK="lm",
    )
    env.update({k: str(v) for k, v in extra_env.items()})
    log = open(out + ".log", "w")
    proc = subprocess.Popen(
        [sys.executable, _WORKER], env=env, stdout=log,
        stderr=subprocess.STDOUT, text=True,
    )
    proc._log_file = log
    return proc


def _finish(proc, what, expect_rc=0, timeout=900):
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    proc._log_file.close()
    with open(proc._log_file.name) as fp:
        log = fp.read()
    if proc.returncode != expect_rc and (
        "Multiprocess computations aren't implemented" in log
    ):
        # platform limit, not a regression: pre-graft jax<=0.4.x has no
        # cross-process CPU collectives, so no two-process topology can run
        pytest.skip(
            "this JAX's CPU backend cannot run multi-process computations "
            "(needs the grafted toolchain or a real accelerator)"
        )
    assert proc.returncode == expect_rc, (
        f"{what}: rc={proc.returncode}, wanted {expect_rc}:\n{log}"
    )


@pytest.mark.slow
def test_kill_peer_emergency_save_and_mesh_reshape_resume(tmp_path):
    """End-to-end elastic recovery with an AGGRESSIVE heartbeat timeout:

    phase A: 2 processes x 4 devices train the LM task with elastic armed
      (beat 0.1s, timeout 0.75s).  Rank 1 SIGKILLs itself entering step 5
      (``kill_peer@5``); rank 0 stalls 2.5s at the same step boundary
      (``stall_step@5:2.5``) so the death is strictly older than the
      timeout when its pre-step liveness check runs.  Rank 0 must raise a
      diagnosed PeerLostError naming rank 1 — not hang — write an
      emergency checkpoint of its committed step-4 state, and exit 0.

    phase B: ONE process x 8 devices relaunches into the same checkpoint
      dir: the mesh-reshape-tolerant restore picks the emergency step up
      (it is newer than the last collective orbax save at step 3), resumes
      mid-epoch at iteration 5, and finishes steps 5..7.

    oracle: an uninterrupted 1-process run of the same config.  The
    stitched trajectory (A steps 0-4 + B steps 5-7) must match it."""
    ckpt = tmp_path / "ckpt"
    base = {
        "MH_CKPT_DIR": ckpt,
        "MH_TRAIN_ITERS": 8,
        "MH_CKPT_INTERVAL": 2,
        "MH_ELASTIC": 1,
        "MH_HB_INTERVAL": 0.1,
        "MH_HB_TIMEOUT": 0.75,
    }
    outs = [str(tmp_path / f"chaos_rank{r}.json") for r in range(2)]
    procs = [
        _spawn(0, 2, _free_ports(1), outs[0], tmp_path, "chaos", 4,
               {**base, "PDT_FAULT_SPEC": "stall_step@5:2.5"}),
        _spawn(1, 2, [0], outs[1], tmp_path, "chaos", 4,
               {**base, "PDT_FAULT_SPEC": "kill_peer@5"}),
    ]
    try:
        _finish(procs[1], "killed rank 1", expect_rc=-9)  # SIGKILL, by design
        _finish(procs[0], "surviving rank 0", expect_rc=0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    with open(outs[0]) as fp:
        survivor = json.load(fp)
    # the diagnosis: named rank, pre-step detection, bounded — not a hang
    assert "rank 1" in survivor["peer_lost"]
    assert survivor["dead_ranks"] == [1]
    assert survivor["mid_step"] is False
    assert survivor["final_iter"] == 5 and len(survivor["losses"]) == 5
    assert survivor["counters"].get("peer_lost") == 1
    assert survivor["counters"].get("elastic_saves") == 1

    # phase B: world size 1, EIGHT local devices — a genuine mesh reshape
    resume_out = str(tmp_path / "resume.json")
    p = _spawn(0, 1, _free_ports(1), resume_out, tmp_path, "resume", 8, base)
    _finish(p, "reshaped resume")
    with open(resume_out) as fp:
        resumed = json.load(fp)
    assert resumed["final_iter"] == 8
    assert len(resumed["losses"]) == 3  # steps 5..7 only — no replay
    assert resumed["counters"].get("elastic_restores") == 1

    # oracle: same config end to end, never interrupted, one process
    oracle_out = str(tmp_path / "oracle.json")
    p = _spawn(0, 1, _free_ports(1), oracle_out, tmp_path, "oracle", 8,
               {"MH_CKPT_DIR": tmp_path / "oracle_ckpt", "MH_TRAIN_ITERS": 8})
    _finish(p, "oracle")
    with open(oracle_out) as fp:
        oracle = json.load(fp)
    assert len(oracle["losses"]) == 8

    np.testing.assert_allclose(
        survivor["losses"], oracle["losses"][:5], rtol=1e-5, atol=1e-6,
        err_msg="pre-kill 2-process trajectory diverged from the oracle",
    )
    np.testing.assert_allclose(
        resumed["losses"], oracle["losses"][5:], rtol=1e-5, atol=1e-6,
        err_msg="post-resume trajectory diverged — mid-epoch resume is not "
                "bit-exact across the mesh reshape",
    )


# ------------------------------------------- in-process end-to-end (1 proc)
@pytest.fixture
def one_device_graft(monkeypatch):
    """``jax.shard_map`` compat-grafted for this test only, pinned to a
    ONE-device mesh (size-1 collectives are identity, so the pre-vma
    graft's autodiff caveat in utils/jax_compat.py does not apply)."""
    import jax

    from pytorch_distributed_training_tpu.engine import paths
    from pytorch_distributed_training_tpu.parallel import make_mesh
    from pytorch_distributed_training_tpu.parallel.mesh import make_sp_mesh

    if not hasattr(jax, "shard_map"):
        from pytorch_distributed_training_tpu.utils import jax_compat

        monkeypatch.setenv("PDT_JAX_COMPAT", "1")
        jax_compat.install()
        wrapper = jax.shard_map
        del jax.shard_map
        monkeypatch.setattr(jax, "shard_map", wrapper, raising=False)
    one = jax.devices()[:1]
    # pin BOTH mesh builders the runner paths use: with >1 device the
    # graft's old-transpose gradients make each device apply its own
    # local update, silently de-replicating the "replicated" state
    monkeypatch.setattr(paths, "make_mesh",
                        lambda *a, **kw: make_mesh(one))
    monkeypatch.setattr(paths, "make_sp_mesh",
                        lambda sp=1, devices=None: make_sp_mesh(sp, one))
    return one


def _recovery_cfg(tmp_path, fault_spec=None):
    train = {
        "optimizer": {
            "name": "SGD", "lr": 0.01, "weight_decay": 1.0e-4, "momentum": 0.9,
        },
        "lr_schedule": {"name": "multi_step", "milestones": [100], "gamma": 0.1},
        "train_iters": 8,
        "print_interval": 100,
        "val_interval": 100,
        "batch_size": 16,
        "num_workers": 0,
        "sync_bn": False,
        "checkpoint": {"dir": str(tmp_path / "ckpt"), "interval": 4},
        "elastic": {"enabled": True, "dir": str(tmp_path / "hb"),
                    "heartbeat_interval": 0.1, "timeout": 0.75},
    }
    if fault_spec:
        train["fault_tolerance"] = {"fault_spec": fault_spec}
    return {
        "dataset": {"name": "synthetic_text", "root": "/unused",
                    "n_classes": 64, "seq_len": 32, "n_samples": 64},
        "training": train,
        "validation": {"batch_size": 16, "num_workers": 0},
        "model": {"name": "TransformerLM", "embed_dim": 32, "depth": 2,
                  "num_heads": 4},
    }


class _LossRunner(Runner):
    """Records the per-step loss; optionally silences a FAKE peer's
    heartbeat once a given step has fully committed (outside the guard),
    simulating that peer's death between steps."""

    def __init__(self, *args, peer=None, peer_stop_iter=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.losses = []
        self._peer = peer
        self._peer_stop_iter = peer_stop_iter

    def train_iter(self, g_img, g_label):
        self.state, loss = self.train_step(self.state, g_img, g_label)
        self.losses.append(float(loss))
        self.scheduler.step()

    def _advance_pipeline(self):
        super()._advance_pipeline()
        if self._peer is not None and self.iter == self._peer_stop_iter:
            self._peer.close()


def _make_recovery_runner(cfg, **runner_kw):
    return _LossRunner(
        num_nodes=1, rank=0, seed=3, dist_url="tcp://127.0.0.1:9907",
        dist_backend="tpu", multiprocessing=False, logger_queue=None,
        global_cfg=cfg, tb_writer_constructor=lambda: None, **runner_kw,
    )


def _run_recovery(cfg, **runner_kw):
    runner = _make_recovery_runner(cfg, **runner_kw)
    runner()
    return runner


@pytest.mark.slow
@pytest.mark.chaos
def test_peer_loss_recovery_end_to_end_single_process(tmp_path, monkeypatch,
                                                      one_device_graft):
    """The full elastic-recovery story, runnable on ANY JAX (no cross-
    process collectives needed): the runner believes it is rank 0 of a
    2-process group whose rank 1 is a real ElasticCoordinator driven by
    the test.  Rank 1 stops beating once step 5 commits; an injected 2.0s
    stall at step 6 ages the silence past the 0.75s timeout, so the
    pre-step liveness gate raises a diagnosed PeerLostError (never a
    hang), the runner emergency-saves its committed step-5 state, and a
    relaunch resumes mid-epoch at step 6 — with the stitched loss
    trajectory exactly matching an uninterrupted run."""
    import pytorch_distributed_training_tpu.engine.runner as runner_mod

    hb_dir = tmp_path / "hb"
    os.makedirs(str(hb_dir), exist_ok=True)
    fault.reset_counters()
    peer = ElasticCoordinator(
        str(hb_dir), process_index=1, num_processes=2,
        heartbeat_interval=0.1, timeout=0.75,
    ).start()
    try:
        with pytest.MonkeyPatch.context() as mp:
            mp.delenv("PDT_FAULT_SPEC", raising=False)
            real = runner_mod.ElasticCoordinator
            mp.setattr(
                runner_mod, "ElasticCoordinator",
                lambda *a, **kw: real(*a, **{**kw, "num_processes": 2}),
            )
            survivor = _make_recovery_runner(
                _recovery_cfg(tmp_path, fault_spec="stall_step@6:2.0"),
                peer=peer, peer_stop_iter=5,
            )
            with pytest.raises(PeerLostError) as ei:
                survivor()
    finally:
        peer.close()
    # diagnosed, pre-step (recoverable), named — and bounded by the stall,
    # not an indefinite hang
    assert "rank 1" in str(ei.value)
    assert ei.value.dead_ranks == (1,)
    assert ei.value.mid_step is False
    assert survivor.iter == 6 and len(survivor.losses) == 6
    assert fault.counters().get("peer_lost") == 1
    assert fault.counters().get("elastic_saves") == 1
    # the emergency dump committed the step-5 state with its MID-epoch
    # pipeline position (6 batches consumed, 4 per epoch -> epoch 1, batch 2)
    meta_path = os.path.join(
        str(tmp_path / "ckpt"), "emergency", "5", "meta_rank0.json"
    )
    assert os.path.exists(meta_path), "no committed emergency checkpoint"
    with open(meta_path) as fp:
        extras = json.load(fp)["extras"]
    assert extras["epoch"] == 1 and extras["batch_in_epoch"] == 2

    # relaunch (same topology): restores the emergency step, resumes at 6
    fault.reset_counters()
    resumed = _run_recovery(_recovery_cfg(tmp_path))
    assert resumed.iter == 8
    assert len(resumed.losses) == 2  # steps 6..7 only — no replay
    assert fault.counters().get("elastic_restores") == 1

    # oracle: same config end to end, never interrupted — the stitched
    # trajectory (survivor steps 0-5 + resumed steps 6-7) must match it
    # EXACTLY: same topology, bit-exact emergency restore, bit-exact
    # mid-epoch batch skip
    oracle = _run_recovery(_recovery_cfg(tmp_path / "oracle"))
    assert len(oracle.losses) == 8
    np.testing.assert_array_equal(
        np.asarray(oracle.losses[:6]), np.asarray(survivor.losses),
        err_msg="pre-kill trajectory diverged from the uninterrupted run",
    )
    np.testing.assert_array_equal(
        np.asarray(oracle.losses[6:]), np.asarray(resumed.losses),
        err_msg="post-resume trajectory diverged from the uninterrupted run",
    )


def test_emergency_save_drains_async_writer_first(tmp_path):
    """save_emergency must drain the in-flight background write before its
    local dump (ISSUE 5): two writers never race on the checkpoint dir,
    and the state the periodic save was carrying commits durably before
    the emergency artifacts appear.  Pinned by gating the orbax write on
    an event the test releases only after save_emergency has been called —
    if the drain were missing, the periodic step would still be
    uncommitted when the emergency dump returned."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.engine import TrainState
    from pytorch_distributed_training_tpu.engine.checkpoint import Checkpointer
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import replicated_sharding
    from pytorch_distributed_training_tpu.parallel.mesh import make_mesh

    opt = SGD(lr=0.1)
    params = {"w": jnp.ones((4, 4))}
    state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
    state = jax.device_put(state, replicated_sharding(make_mesh()))

    ck = Checkpointer(str(tmp_path / "c"), interval=1, async_save=True)
    gate = threading.Event()
    orig_save = ck._manager.save

    def gated_save(step, *a, **kw):
        gate.wait(10.0)  # hold the background write until released
        return orig_save(step, *a, **kw)

    ck._manager.save = gated_save
    try:
        ck.save(1, state)  # enqueued; the writer thread is parked on the gate
        assert ck.all_steps() == []  # provably still in flight
        threading.Timer(0.2, gate.set).start()
        ck.save_emergency(2, state)
        committed_before_emergency = ck.all_steps()
    finally:
        gate.set()
        ck._manager.save = orig_save
        ck.close()
    # the drain ran first: the gated periodic write was durable before the
    # emergency dump returned
    assert committed_before_emergency == [1]
    assert ck.latest_emergency() == 2
